"""hidden-sync: implicit host round trips on serve-path modules.

The serving budget is "2 dispatches + 2 fetches per retrieve→rerank call"
(ops/dispatch_counter.py proves it at runtime; README serving docs).  A
single stray ``float(score)`` on a device array, an un-``submit``ted
``predict`` call, or a ``block_until_ready`` quietly adds a full tunnel
RTT (~70 ms) to every serve — and nothing fails, it just gets slower.
This rule makes those host round trips lexically visible in the modules
marked serve-path (``# pathway: serve-path`` marker, plus the default
list in core.py).

Checks, per function scope:

- **blocking dispatch+sync**: a scope that both dispatches a jitted call
  and coerces its result to host (``np.asarray``/``float``/``int``/
  ``.item()``) is a synchronous round trip.  The sanctioned pattern is
  submit/complete: dispatch in one scope, fetch inside the completion
  closure (closures are separate scopes, so the async pattern is clean);
- **``.block_until_ready()``** anywhere on a serve path — latency fences
  belong in bench/tests, not serving code;
- **un-``submit``ted ``predict``**: ``.predict(...)`` blocks on its
  result; serve paths must use ``.submit(...)`` and complete later;
- **budget accounting** (only in modules that import the dispatch
  counter): a scope that dispatches a jitted call must call
  ``record_dispatch``, and a scope that fetches (host coercion of a
  device value) must call ``record_fetch`` — otherwise the runtime
  dispatch/fetch assertion silently under-counts and the "two round
  trips" claim stops being ground truth.

  **Cache-wrapper exemption** (pathway_tpu/cache): a scope named
  ``_cached_*`` / ``get_or_*`` wraps its dispatch behind a cache lookup
  — the launch fires only on a miss and is booked inside the CALLER's
  logical dispatch group (``record_dispatch(tag, shards=<launches>)``),
  so the budget checks skip wrapper scopes.  A cache lookup guarding a
  dispatch is not a hidden sync; the blocking dispatch+sync check and
  every lock-discipline check still apply inside wrappers.
- **fan-out width** (only in budget modules): a scope that fans stream
  I/O out in a loop — the partitioned fabric's scatter-gather
  (serve/fabric.py ``fabric.scatter``/``fabric.gather``), same shape as
  the sharded index's per-shard launches — and books dispatches must
  declare the physical width on the booking
  (``record_dispatch(tag, shards=N)``, 1 logical + N physical).
  Booking an H-way scatter without ``shards=`` records one physical
  send and the runtime shard-dispatch counters silently under-count by
  H−1.  See ``registry.is_dispatch_booking`` /
  ``registry.booking_declares_fanout`` for the convention.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .core import ModuleContext, Rule
from .registry import (
    booking_declares_fanout,
    dotted_name,
    is_cache_wrapper,
    is_device_value_arg,
    is_device_value_base,
    is_jit_call,
    is_stream_io,
    scope_jit_and_device_vars,
    walk_scope,
)

__all__ = ["HiddenSyncRule"]

_COERCIONS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
              "float", "int", "jax.device_get"}


class HiddenSyncRule(Rule):
    name = "hidden-sync"
    salt_sources = ("hidden_sync.py",)
    description = (
        "implicit host sync / unaccounted dispatch on a serve-path module"
    )

    def run(self, ctx: ModuleContext) -> None:
        if not ctx.serve_path:
            return
        self._budget_module = (
            "record_dispatch" in ctx.source or "record_fetch" in ctx.source
        )
        self._visit_scope(ctx, ctx.tree, None, None)

    def _visit_scope(self, ctx, scope, inherited_fns, inherited_vars) -> None:
        jit_fns, device_vars = scope_jit_and_device_vars(
            scope, ctx.jit_names, inherited_fns, inherited_vars
        )
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._check_scope(ctx, scope, jit_fns, device_vars)
        for child in ast.iter_child_nodes(scope):
            self._recurse_defs(ctx, child, jit_fns, device_vars)

    def _recurse_defs(self, ctx, node, fns, dvars) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._visit_scope(ctx, node, fns, dvars)
            return
        if isinstance(node, ast.Lambda):
            return
        for child in ast.iter_child_nodes(node):
            self._recurse_defs(ctx, child, fns, dvars)

    def _check_scope(self, ctx, scope, jit_fns, device_vars) -> None:
        # jitted functions themselves run ON device; their bodies are not
        # host code (np/float inside them is trace-time, not a sync)
        if scope.name in ctx.jit_names:
            return
        # cache wrappers (_cached_* / get_or_*): the miss-path dispatch
        # is accounted by the caller's dispatch group, so the BUDGET
        # checks below are waived — sync-in-scope checks still apply
        cache_wrapper = is_cache_wrapper(scope.name)
        dispatches: List[ast.Call] = []
        syncs: List[Tuple[ast.Call, str]] = []
        bookings: List[ast.Call] = []
        has_record_dispatch = False
        has_record_fetch = False
        for node in walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            leaf = callee.rsplit(".", 1)[-1] if callee else ""
            if leaf == "record_dispatch":
                has_record_dispatch = True
                bookings.append(node)
            elif leaf == "record_fetch":
                has_record_fetch = True
                bookings.append(node)
            elif is_jit_call(node, jit_fns):
                dispatches.append(node)
            elif leaf == "block_until_ready":
                ctx.report(
                    self.name, node,
                    f"`{callee}()` on a serve path — a blocking device "
                    "fence costs a full RTT per call; fences belong in "
                    "bench/tests",
                )
            elif leaf == "predict" and isinstance(node.func, ast.Attribute):
                ctx.report(
                    self.name, node,
                    f"blocking `{callee}(...)` on a serve path — use "
                    "`.submit(...)` and complete asynchronously so "
                    "consecutive serves pipeline",
                )
            elif callee in _COERCIONS and is_device_value_arg(
                node, jit_fns, device_vars
            ):
                syncs.append((node, callee))
            elif leaf == "item" and is_device_value_base(node, device_vars):
                syncs.append((node, callee or ".item"))
        for node, callee in syncs:
            if dispatches:
                ctx.report(
                    self.name, node,
                    f"`{callee}` of a device value in the same scope that "
                    "dispatched it — a synchronous round trip; move the "
                    "fetch into a completion closure (submit/complete)",
                )
            elif self._budget_module and not has_record_fetch and not cache_wrapper:
                ctx.report(
                    self.name, node,
                    f"`{callee}` fetches a device value but the scope "
                    "never calls record_fetch — the serving fetch budget "
                    "under-counts this round trip",
                )
        if cache_wrapper:
            return
        # fan-out width: a booked scope whose stream I/O fans out in a
        # loop (the scatter-gather shape) must declare the physical
        # width on the booking — record_dispatch(tag, shards=N)
        if self._budget_module and bookings and not any(
            booking_declares_fanout(b) for b in bookings
        ):
            fanned = self._loop_stream_io(scope)
            if fanned is not None:
                ctx.report(
                    self.name, bookings[0],
                    f"stream fan-out (`{fanned}` inside a loop) booked "
                    "without its physical width — book the scatter as "
                    "record_dispatch(tag, shards=N) / record_fetch(tag, "
                    "shards=N) so the budget stays 1 logical + N physical",
                )
        if self._budget_module and dispatches and not has_record_dispatch:
            for node in dispatches:
                ctx.report(
                    self.name, node,
                    "jitted dispatch without record_dispatch in scope — "
                    "the serving dispatch budget under-counts this launch",
                )

    @staticmethod
    def _loop_stream_io(scope) -> Optional[str]:
        """The dotted spelling of the first stream I/O call lexically
        inside a loop of this scope (nested defs excluded), or None."""
        for node in walk_scope(scope):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for inner in walk_scope(node):
                if isinstance(inner, ast.Call):
                    spelled = is_stream_io(inner)
                    if spelled:
                        return spelled
        return None
