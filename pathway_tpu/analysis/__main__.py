"""CLI: ``python -m pathway_tpu.analysis [paths...]``.

Prints one ``path:line:col: rule: message`` diagnostic per unsuppressed
finding and exits 1 if any exist (0 on a clean tree) — the same contract
the tier-1 gate test asserts through the API.  ``--show-suppressed``
audits every pragma allowance alongside the live findings.

Machine-readable output: ``--format json`` emits ONE JSON document
(``{"findings": [...], "live": N, "suppressed": M}`` — the CI-friendly
shape); ``--format jsonl`` (alias: the legacy ``--json`` flag) emits one
JSON record per finding; ``--format sarif`` emits a SARIF 2.1.0 log so
CI can annotate findings directly onto PR diffs (suppressed findings
ride along as SARIF suppressions).  Exit codes are identical across
formats.

``--check-pragmas`` additionally reports every suppression pragma that
no longer suppresses any finding (stale waivers rot: the violation they
blessed was fixed or moved, and a dead pragma silently blesses the NEXT
violation near it).  ``PATHWAY_ANALYSIS_CACHE=<dir>`` arms the
content-hash incremental cache so repo-wide runs re-parse only changed
modules.

The analysis modules themselves are pure stdlib + AST (no jax import),
so the lint runs anywhere — pre-commit, CI boxes with no accelerator, a
wedged-tunnel host — in well under a second once Python is up.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from .core import Finding, analyze_paths, default_rules, stale_pragma_findings

# SARIF severity: every rule here is a correctness gate, so findings map
# to "error"; suppressed ones carry a SARIF suppression object instead
_SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def render_sarif(findings: Sequence[Finding]) -> dict:
    """One SARIF 2.1.0 log for the whole run — deterministic (findings
    arrive sorted), so the golden-file test can assert bytes."""
    rule_ids = sorted({f.rule for f in findings})
    descriptions = {
        rule.name: rule.description for rule in default_rules()
    }
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace("\\", "/"),
                        },
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": max(1, f.col + 1),
                        },
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": f.reason or "",
                }
            ]
        results.append(result)
    return {
        "$schema": _SARIF_SCHEMA,
        "version": _SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "pathway-analysis",
                        "informationUri": (
                            "python -m pathway_tpu.analysis"
                        ),
                        "rules": [
                            {
                                "id": rid,
                                "shortDescription": {
                                    "text": descriptions.get(rid, rid)
                                },
                            }
                            for rid in rule_ids
                        ],
                    }
                },
                "results": results,
            }
        ],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.analysis",
        description="Hot-path lint: lock-discipline, hidden-sync, "
        "recompile-hazard, lock-order, value-flow, knob-discipline.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["pathway_tpu"],
        help="files or directories to analyze (default: pathway_tpu)",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their pragma reasons",
    )
    parser.add_argument(
        "--check-pragmas", action="store_true",
        help="also report suppression pragmas that no longer suppress "
        "any finding (stale waivers)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "jsonl", "sarif"),
        default="text", dest="fmt",
        help="output format: human text (default), one JSON document "
        "(json), one JSON record per finding (jsonl), or a SARIF 2.1.0 "
        "log for CI diff annotation (sarif)",
    )
    parser.add_argument(
        "--json", action="store_const", const="jsonl", dest="fmt",
        help="legacy alias for --format jsonl",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule names + descriptions and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.name}: {rule.description}")
        return 0

    findings, pragma_map = analyze_paths(args.paths, return_pragmas=True)
    if args.check_pragmas:
        findings = list(findings) + stale_pragma_findings(pragma_map)
    live = [f for f in findings if not f.suppressed]
    n_sup = len(findings) - len(live)
    if args.fmt == "sarif":
        print(json.dumps(render_sarif(findings), indent=1, sort_keys=True))
        return 1 if live else 0
    if args.fmt == "json":
        # one complete document: what a CI step or the tier-1 gate wants
        # to parse — every finding (suppressed ones carry their reason),
        # plus the counts the exit code is derived from
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in findings],
                    "live": len(live),
                    "suppressed": n_sup,
                }
            )
        )
        return 1 if live else 0
    shown = findings if args.show_suppressed else live
    for f in shown:
        if args.fmt == "jsonl":
            print(json.dumps(f.__dict__))
        else:
            print(f.format())
    print(
        f"{len(live)} finding{'s' if len(live) != 1 else ''} "
        f"({n_sup} suppressed)",
        file=sys.stderr,
    )
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
