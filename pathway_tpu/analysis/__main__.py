"""CLI: ``python -m pathway_tpu.analysis [paths...]``.

Prints one ``path:line:col: rule: message`` diagnostic per unsuppressed
finding and exits 1 if any exist (0 on a clean tree) — the same contract
the tier-1 gate test asserts through the API.  ``--show-suppressed``
audits every pragma allowance alongside the live findings.

Machine-readable output: ``--format json`` emits ONE JSON document
(``{"findings": [...], "live": N, "suppressed": M}`` — the CI-friendly
shape); ``--format jsonl`` (alias: the legacy ``--json`` flag) emits one
JSON record per finding.  Exit codes are identical across formats.

The analysis modules themselves are pure stdlib + AST (no jax import),
so the lint runs anywhere — pre-commit, CI boxes with no accelerator, a
wedged-tunnel host — in well under a second once Python is up.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from .core import analyze_paths, default_rules


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.analysis",
        description="Hot-path lint: lock-discipline, hidden-sync, "
        "recompile-hazard.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["pathway_tpu"],
        help="files or directories to analyze (default: pathway_tpu)",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their pragma reasons",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "jsonl"), default="text",
        dest="fmt",
        help="output format: human text (default), one JSON document "
        "(json), or one JSON record per finding (jsonl)",
    )
    parser.add_argument(
        "--json", action="store_const", const="jsonl", dest="fmt",
        help="legacy alias for --format jsonl",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule names + descriptions and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.name}: {rule.description}")
        return 0

    findings = analyze_paths(args.paths)
    live = [f for f in findings if not f.suppressed]
    n_sup = len(findings) - len(live)
    if args.fmt == "json":
        # one complete document: what a CI step or the tier-1 gate wants
        # to parse — every finding (suppressed ones carry their reason),
        # plus the counts the exit code is derived from
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in findings],
                    "live": len(live),
                    "suppressed": n_sup,
                }
            )
        )
        return 1 if live else 0
    shown = findings if args.show_suppressed else live
    for f in shown:
        if args.fmt == "jsonl":
            print(json.dumps(f.__dict__))
        else:
            print(f.format())
    print(
        f"{len(live)} finding{'s' if len(live) != 1 else ''} "
        f"({n_sup} suppressed)",
        file=sys.stderr,
    )
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
