"""lock-order: whole-program lock-acquisition hierarchy + deadlock cycles.

The fourth analyzer family.  ``lock_discipline`` polices what happens
*inside* one lock body; this family polices the ORDER locks are taken in
across the whole serve stack — the dimension where deadlocks live.  The
reference engine gets this for free from the borrow checker; our Python
thread fabric (scheduler, decode slot pool, cache tiers, IVF + forward
indexes, shard group, exchange plane, observe stack) holds 60+ distinct
locks with no cross-module guarantee.  This pass makes the guarantee:

1. **Site discovery** — every attribute-rooted ``threading.Lock`` /
   ``RLock`` / ``Condition`` creation (``self._lock``, ``self._pool_lock``,
   ``_registry_lock``, ``self._send_locks[peer]``) gets a stable
   ``module.Class.attr`` identity.  ``Condition(self._qlock)`` records an
   ALIAS: acquiring the condition is acquiring the wrapped lock.
2. **Nested-acquisition graph** — walking ``with <lock>:`` bodies, plus
   interprocedural edges through the same call-resolution conventions the
   other rules use (``registry.py``): ``self.helper()``, same-module
   functions, imported-module functions, ``retry_call("site", fn, ...)``
   wrappers, and program-unique method names (``.get_rows``,
   ``.observe_ns``) all carry a held lock into their callee's
   acquisitions.
3. **Checks** against the declared hierarchy (``lock_ranks.py``:
   ``observe < cache < model < index < shard < scheduler < pool``,
   acquired in DESCENDING rank order):

   - **rank inversion** — a higher-rank lock acquired while holding a
     lower-rank one;
   - **deadlock cycle** — ANY cycle in the observed graph (rank-waived
     or not), reported with the full witness path;
   - **self-deadlock** — a non-reentrant ``Lock`` re-acquired while
     already held (lexically or through a helper);
   - **Condition.wait holding a second lock** — the wait releases only
     the condition's own lock; every other held lock blocks its owner
     for the whole wait;
   - **lock acquire inside a jitted dispatch scope** — a ``with <lock>:``
     in a ``jax.jit`` function body runs at trace time (or never), which
     is always a bug (bridges to the hidden-sync family's jit registry).

The runtime twin (``analysis/sanitizer.py``, ``PATHWAY_LOCK_SANITIZER=1``)
enforces the SAME hierarchy on live acquisition interleavings — the
dynamic oracle that confirms or refutes every static edge.

A reviewed exception is waived at the acquisition site::

    with self._lock:  # pathway: allow(lock-order): <rank exception + why safe>
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleContext, Rule
from .lock_ranks import rank_name, rank_of_path, rank_of_receiver, table
from .registry import dotted_name

__all__ = ["LockOrderRule", "module_dotted", "module_lock_sites"]

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
}
# same terminal-identifier heuristic as registry.is_lock_context, applied
# per name so both passes agree on what spells a lock
_LOCK_NAME_RE = re.compile(r"lock|mutex|cv\b|cond", re.IGNORECASE)
_WAIT_ATTRS = ("wait", "wait_for")
# generic container/stdlib method names never resolved through the
# program-unique-method fallback (a repo class happening to define one
# must not vacuum every `x.append()` call into its lock footprint)
_GENERIC_METHODS = frozenset(
    {
        "append", "add", "get", "put", "pop", "popleft", "update", "remove",
        "clear", "close", "stop", "start", "join", "wait", "notify",
        "notify_all", "acquire", "release", "items", "keys", "values",
        "set", "is_set", "result", "submit", "send", "recv", "read",
        "write", "encode", "decode", "copy", "extend", "sort", "index",
        "count", "flush", "open", "reset", "render", "sample", "search",
        "build", "advance", "serve", "run", "next_id", "save", "load",
    }
)
_MAX_WITNESS = 6  # interprocedural witness-chain depth cap


def module_dotted(display_path: str) -> str:
    """Stable dotted module id from a repo-relative display path:
    ``pathway_tpu/serve/scheduler.py`` → ``serve.scheduler``;
    ``fixtures/mod.py`` → ``fixtures.mod``."""
    path = display_path.replace("\\", "/").replace(os.sep, "/")
    if path.endswith(".py"):
        path = path[: -len(".py")]
    parts = [p for p in path.split("/") if p and p != "."]
    if parts and parts[0] == "pathway_tpu":
        parts = parts[1:] or ["pathway_tpu"]
    return ".".join(parts)


class _Extractor(ast.NodeVisitor):
    """One pass over a module: lock sites, aliases, per-function
    acquisition facts, and the module-local findings (cond-wait-second-
    lock, lock-in-jit)."""

    def __init__(self, ctx: ModuleContext, rule_name: str):
        self.ctx = ctx
        self.rule_name = rule_name
        self.mod = module_dotted(ctx.display_path)
        self.sites: Dict[str, dict] = {}
        self.aliases: Dict[str, str] = {}
        self.classes: Dict[str, List[str]] = {}
        self.functions: Dict[str, dict] = {}
        self.imports: Dict[str, str] = {}
        self._collect_imports(ctx.tree)
        self._collect_sites(ctx.tree)
        self._walk_functions(ctx.tree)

    # -- imports: local alias -> dotted module (for alias.func() edges) --
    def _collect_imports(self, tree: ast.Module) -> None:
        pkg_parts = self.mod.split(".")[:-1]
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for n in node.names:
                    name = n.name
                    if name == "pathway_tpu" or name.startswith("pathway_tpu."):
                        target = name[len("pathway_tpu."):] or "pathway_tpu"
                        self.imports[n.asname or name.split(".")[-1]] = target
            elif isinstance(node, ast.ImportFrom):
                base: Optional[List[str]]
                if node.level == 0:
                    raw = node.module or ""
                    if raw == "pathway_tpu":
                        base = []
                    elif raw.startswith("pathway_tpu."):
                        base = raw[len("pathway_tpu."):].split(".")
                    else:
                        base = None
                else:
                    up = node.level - 1
                    if up > len(pkg_parts):
                        base = None
                    else:
                        base = list(
                            pkg_parts[: len(pkg_parts) - up]
                        )
                        if node.module:
                            base.extend(node.module.split("."))
                if base is None:
                    continue
                for n in node.names:
                    target = ".".join(base + [n.name]) if n.name != "*" else None
                    if target:
                        self.imports[n.asname or n.name] = target

    # -- site discovery ---------------------------------------------------
    def _collect_sites(self, tree: ast.Module) -> None:
        # walk with class context so `self._lock = threading.Lock()`
        # inside `def __init__` lands on the enclosing class
        def visit(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self.classes.setdefault(
                        child.name,
                        [
                            b for b in (
                                dotted_name(base) for base in child.bases
                            )
                            if b
                        ],
                    )
                    visit(child, child.name)
                    continue
                if isinstance(child, ast.Assign):
                    self._maybe_site(child, cls)
                visit(child, cls)

        visit(tree, None)

    def _maybe_site(self, node: ast.Assign, cls: Optional[str]) -> None:
        value = node.value
        if not isinstance(value, ast.Call):
            return
        ctor = dotted_name(value.func)
        kind = _LOCK_CTORS.get(ctor or "")
        if kind is None:
            return
        for tgt in node.targets:
            sid = self._site_id_for_target(tgt, cls)
            if sid is None:
                continue
            self.sites.setdefault(
                sid, {"kind": kind, "line": node.lineno}
            )
            if kind == "condition" and value.args:
                # Condition(self._qlock): acquiring the condition IS
                # acquiring the wrapped lock — record the alias
                wrapped = self._resolve_lock_name(
                    dotted_name(value.args[0]), cls, None
                )
                if wrapped is not None and wrapped != sid:
                    self.aliases[sid] = wrapped

    def _site_id_for_target(
        self, tgt: ast.AST, cls: Optional[str]
    ) -> Optional[str]:
        while isinstance(tgt, ast.Subscript):  # self._send_locks[peer]
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute):
            base = dotted_name(tgt.value)
            if base == "self" and cls:
                return f"{self.mod}.{cls}.{tgt.attr}"
            return None
        if isinstance(tgt, ast.Name):
            if cls is None:
                return f"{self.mod}.{tgt.id}"
            return f"{self.mod}.{cls}.{tgt.id}"
        return None

    # -- per-function facts ----------------------------------------------
    def _walk_functions(self, tree: ast.Module) -> None:
        def visit(node: ast.AST, cls: Optional[str], fn: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, None)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    local = f"{cls}.{child.name}" if cls else child.name
                    self._extract_function(child, cls, local)
                    # nested defs inside it are found by _extract_function's
                    # own recursion guard walking here too:
                    visit(child, cls, local)
                else:
                    visit(child, cls, fn)

        visit(tree, None, None)
        # module top level executes at import: treat as one function
        self._extract_function(tree, None, "<module>", top_level=True)

    def _extract_function(
        self,
        scope: ast.AST,
        cls: Optional[str],
        local: str,
        top_level: bool = False,
    ) -> None:
        if local in self.functions and not top_level:
            # a name collision (overload by branch) keeps the first body
            return
        rec = {"direct": [], "edges": [], "calls": [], "waits": []}
        in_jit = (
            not top_level
            and isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef))
            and scope.name in self.ctx.jit_names
        )

        def walk(node: ast.AST, stack: List[Tuple[str, int]]) -> None:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                return  # separate execution scope
            if isinstance(node, ast.With):
                inner_stack = stack
                for item in node.items:
                    # the item's context expression evaluates BEFORE its
                    # lock is held (but under any earlier items/locks)
                    for sub in ast.iter_child_nodes(item):
                        walk(sub, inner_stack)
                    sid = self._resolve_lock_name(
                        dotted_name(item.context_expr), cls, local
                    )
                    if sid is None:
                        continue
                    self._record_acquire(
                        rec, sid, node.lineno, inner_stack, in_jit, node
                    )
                    inner_stack = inner_stack + [(sid, node.lineno)]
                for stmt in node.body:
                    walk(stmt, inner_stack)
                return
            if isinstance(node, ast.Call):
                self._record_call(rec, node, cls, local, stack)
            for child in ast.iter_child_nodes(node):
                walk(child, stack)

        for child in ast.iter_child_nodes(scope):
            walk(child, [])
        if any(rec[k] for k in rec):
            self.functions[local] = rec

    def _record_acquire(
        self,
        rec: dict,
        sid: str,
        line: int,
        stack: Sequence[Tuple[str, int]],
        in_jit: bool,
        node: ast.AST,
    ) -> None:
        rec["direct"].append([sid, line])
        for held, _hline in stack:
            rec["edges"].append([held, sid, line])
        if in_jit:
            self.ctx.report(
                self.rule_name, node,
                f"lock `{sid}` acquired inside a jitted dispatch scope — "
                "a `with <lock>:` in a jax.jit body runs at TRACE time "
                "(or is constant-folded away), never per step; locking "
                "belongs in the host-side caller",
            )

    def _record_call(
        self,
        rec: dict,
        call: ast.Call,
        cls: Optional[str],
        local: str,
        stack: Sequence[Tuple[str, int]],
    ) -> None:
        held = [s for s, _l in stack]
        func = call.func
        refs: List[List[str]] = []
        leaf = None
        if isinstance(func, ast.Name):
            leaf = func.id
            refs.append(["bare", func.id])
        elif isinstance(func, ast.Attribute):
            leaf = func.attr
            recv = dotted_name(func.value)
            if recv == "self":
                refs.append(["self", func.attr])
            elif recv is not None and recv in self.imports:
                refs.append(["mod", self.imports[recv], func.attr])
            else:
                refs.append(["meth", func.attr])
            # explicit acquire()/wait() on a lock-spelled receiver
            if recv is not None:
                rsid = self._resolve_lock_name(recv, cls, local)
                if rsid is not None and func.attr == "acquire":
                    rec["direct"].append([rsid, call.lineno])
                    for h, _hl in stack:
                        rec["edges"].append([h, rsid, call.lineno])
                if rsid is not None and func.attr in _WAIT_ATTRS:
                    others = sorted(
                        {
                            self._canon_local(s)
                            for s in held
                        }
                        - {self._canon_local(rsid)}
                    )
                    if others:
                        self.ctx.report(
                            self.rule_name, call,
                            f"`{recv}.{func.attr}()` while holding "
                            f"{', '.join('`%s`' % o for o in others)} — "
                            "Condition.wait releases only its OWN lock; "
                            "every other held lock stays held for the "
                            "whole wait, wedging its waiters (release "
                            "the second lock before waiting)",
                        )
                    rec["waits"].append(
                        [rsid, others, call.lineno]
                    )
        # retry_call("site", fn, ...) dispatches fn: the held locks reach
        # fn's acquisitions through the wrapper (the robust-retry lesson)
        if leaf == "retry_call":
            for arg in call.args:
                name = dotted_name(arg)
                if name is None:
                    continue
                parts = name.split(".")
                if parts[0] == "self" and len(parts) == 2:
                    refs.append(["self", parts[1]])
                elif len(parts) == 1:
                    refs.append(["bare", parts[0]])
        if refs:
            rec["calls"].append([held, refs, call.lineno])

    def _canon_local(self, sid: str) -> str:
        seen = set()
        while sid in self.aliases and sid not in seen:
            seen.add(sid)
            sid = self.aliases[sid]
        return sid

    # -- lock-expression resolution --------------------------------------
    def _resolve_lock_name(
        self, name: Optional[str], cls: Optional[str], local: Optional[str]
    ) -> Optional[str]:
        if name is None:
            return None
        parts = name.split(".")
        leaf = parts[-1]
        if not _LOCK_NAME_RE.search(leaf):
            return None
        if parts[0] == "self" and len(parts) == 2 and cls:
            for k in self._mro(cls):
                sid = f"{self.mod}.{k}.{leaf}"
                if sid in self.sites:
                    return sid
            owners = [
                c for c in self.classes
                if f"{self.mod}.{c}.{leaf}" in self.sites
            ]
            if len(owners) == 1:
                return f"{self.mod}.{owners[0]}.{leaf}"
            # attribute on self with no in-module definition (assigned
            # externally or in a cross-module base): stable per-class id
            return f"{self.mod}.{cls}.{leaf}"
        if len(parts) == 1:
            sid = f"{self.mod}.{leaf}"
            if sid in self.sites:
                return sid
            if local is not None:
                fsid = f"{self.mod}.{local}.{leaf}"
                if fsid in self.sites:
                    return fsid
            # parameter / local spelled like a lock (fixture style):
            # identity is module-local
            return f"{self.mod}.<{leaf}>"
        if len(parts) == 2 and parts[0] in self.imports:
            # module-global lock through an import alias
            # (`_recorder._registry_lock`)
            return f"{self.imports[parts[0]]}.{leaf}"
        # non-self receiver (child._lock, plane._cv): the defining class
        # is unknown statically — module-local opaque identity, unranked,
        # still a node for cycle detection
        return f"{self.mod}.<{name}>"

    def _mro(self, cls: str) -> List[str]:
        out, queue, seen = [], [cls], set()
        while queue:
            c = queue.pop(0)
            if c in seen:
                continue
            seen.add(c)
            out.append(c)
            queue.extend(
                b.split(".")[-1] for b in self.classes.get(c, ())
            )
        return out

    def summary(self) -> dict:
        return {
            "mod": self.mod,
            "sites": self.sites,
            "aliases": self.aliases,
            "classes": self.classes,
            "functions": self.functions,
            "imports": self.imports,
        }


class LockOrderRule(Rule):
    name = "lock-order"
    salt_sources = ("lock_order.py", "lock_ranks.py")
    description = (
        "lock-acquisition hierarchy: rank inversions against the declared "
        f"table ({table()}), deadlock cycles with witness paths, "
        "Condition.wait holding a second lock, locks in jitted scopes"
    )

    def __init__(self) -> None:
        self._summaries: Dict[str, dict] = {}

    # -- per-module side --------------------------------------------------
    def run(self, ctx: ModuleContext) -> None:
        extractor = _Extractor(ctx, self.name)
        self._summaries[ctx.display_path] = extractor.summary()

    def dump_summary(self, display_path: str) -> Optional[dict]:
        return self._summaries.get(display_path)

    def load_summary(self, display_path: str, summary: dict) -> None:
        self._summaries[display_path] = summary

    # -- whole-program side ----------------------------------------------
    def finalize(self) -> List[Finding]:
        prog = _Program(self._summaries)
        return prog.findings()


class _Program:
    """The global graph: merged sites/aliases, resolved call graph,
    transitive acquire sets, and the rank/cycle checks."""

    def __init__(self, summaries: Dict[str, dict]):
        self.summaries = summaries
        self.site_info: Dict[str, dict] = {}    # sid -> {kind, path}
        self.aliases: Dict[str, str] = {}
        self.funcs: Dict[str, dict] = {}        # gfid -> record
        self.func_path: Dict[str, str] = {}     # gfid -> display path
        self.func_mod: Dict[str, str] = {}
        self.module_funcs: Dict[Tuple[str, str], str] = {}
        self.method_index: Dict[str, List[str]] = {}
        self.class_info: Dict[Tuple[str, str], List[str]] = {}
        self.mod_imports: Dict[str, Dict[str, str]] = {}
        for path in sorted(summaries):
            s = summaries[path]
            mod = s["mod"]
            self.mod_imports[mod] = s.get("imports", {})
            for sid, info in s["sites"].items():
                self.site_info.setdefault(
                    sid, {"kind": info["kind"], "path": path}
                )
            self.aliases.update(s["aliases"])
            for cls, bases in s["classes"].items():
                self.class_info[(mod, cls)] = bases
            for local, rec in s["functions"].items():
                gfid = f"{mod}::{local}"
                self.funcs[gfid] = rec
                self.func_path[gfid] = path
                self.func_mod[gfid] = mod
                if "." in local:
                    cls, meth = local.rsplit(".", 1)
                    self.method_index.setdefault(meth, []).append(gfid)
                elif local != "<module>":
                    self.module_funcs[(mod, local)] = gfid
        self._canon_cache: Dict[str, str] = {}
        self._resolved_calls: Dict[str, List[Tuple[List[str], List[str], int]]] = {}
        self._resolve_all_calls()
        self._acq = self._fixpoint_acquires()

    def canon(self, sid: str) -> str:
        cached = self._canon_cache.get(sid)
        if cached is not None:
            return cached
        self._canon_cache[sid] = out = self._canon_uncached(sid)
        return out

    def _canon_uncached(self, sid: str) -> str:
        seen = set()
        while True:
            if sid in self.aliases and sid not in seen:
                seen.add(sid)
                sid = self.aliases[sid]
                continue
            if sid not in self.site_info:
                remapped = self._remap_inherited(sid)
                if remapped is not None and remapped not in seen:
                    seen.add(sid)
                    sid = remapped
                    continue
            return sid

    def _remap_inherited(self, sid: str) -> Optional[str]:
        """A ``self.X`` lock with no in-module definition fabricates a
        per-subclass id (``serve.decode.ContinuousDecoder._qlock``); if
        the attribute is actually DEFINED by a cross-module base class
        (``serve.scheduler._CoalescerBase._qlock``), remap to the
        defining site so both spellings name ONE graph node — a real
        ABBA spanning the two modules must not split across them."""
        for (mod, cls) in self.class_info:
            prefix = f"{mod}.{cls}."
            if not sid.startswith(prefix):
                continue
            attr = sid[len(prefix):]
            if not attr or "." in attr:
                continue
            target = self._find_site_in_bases(mod, cls, attr, set())
            if target is not None:
                return target
        return None

    def _find_site_in_bases(
        self, mod: str, cls: str, attr: str, seen: Set[Tuple[str, str]]
    ) -> Optional[str]:
        if (mod, cls) in seen:
            return None
        seen.add((mod, cls))
        cand = f"{mod}.{cls}.{attr}"
        if cand in self.site_info:
            return cand
        for base in self.class_info.get((mod, cls), ()):
            leaf = base.split(".")[-1]
            if (mod, leaf) in self.class_info:
                got = self._find_site_in_bases(mod, leaf, attr, seen)
                if got is not None:
                    return got
                continue
            # cross-module base: resolve the base name through the
            # subclass module's imports (`from .scheduler import Base`)
            target = self.mod_imports.get(mod, {}).get(leaf)
            if target and "." in target:
                tmod, tcls = target.rsplit(".", 1)
                if (tmod, tcls) in self.class_info:
                    got = self._find_site_in_bases(tmod, tcls, attr, seen)
                    if got is not None:
                        return got
        return None

    def _rank(self, sid: str) -> Optional[int]:
        info = self.site_info.get(sid)
        if info is not None:
            return rank_of_path(info["path"])
        # opaque receiver lock (`mod.<child._lock>`): the receiver
        # spelling carries the domain by convention (lock_ranks)
        m = re.match(r".*\.<(\w+)\.", sid)
        if m:
            return rank_of_receiver(m.group(1))
        return None

    def _kind(self, sid: str) -> Optional[str]:
        info = self.site_info.get(sid)
        return None if info is None else info["kind"]

    # -- call resolution --------------------------------------------------
    def _resolve_ref(self, gfid: str, ref: Sequence[str]) -> List[str]:
        mod = self.func_mod[gfid]
        kind = ref[0]
        if kind == "self":
            meth = ref[1]
            local = gfid.split("::", 1)[1]
            cls = local.rsplit(".", 1)[0] if "." in local else None
            if cls is not None:
                for k in self._mro(mod, cls):
                    cand = f"{mod}::{k}.{meth}"
                    if cand in self.funcs:
                        return [cand]
            # program-unique fallback ONLY when the class has a base the
            # module walk could not resolve (a cross-module parent may
            # define the method).  A base-less class calling `self.X()`
            # with no such method is calling an ATTRIBUTE (a stored
            # callable) — resolving that by name invents false edges.
            if cls is not None and self._has_external_base(mod, cls):
                return self._unique_method(meth)
            return []
        if kind == "bare":
            cand = self.module_funcs.get((mod, ref[1]))
            return [cand] if cand else []
        if kind == "mod":
            target, func = ref[1], ref[2]
            for m in (target, target + ".__init__"):
                cand = self.module_funcs.get((m, func))
                if cand:
                    return [cand]
            return []
        if kind == "meth":
            return self._unique_method(ref[1])
        return []

    def _has_external_base(self, mod: str, cls: str) -> bool:
        for c in self._mro(mod, cls):
            for base in self.class_info.get((mod, c), ()):
                leaf = base.split(".")[-1]
                if (mod, leaf) not in self.class_info and leaf not in (
                    "object", "Exception", "ABC",
                ):
                    return True
        return False

    def _unique_method(self, meth: str) -> List[str]:
        if meth in _GENERIC_METHODS:
            return []
        owners = self.method_index.get(meth, ())
        return list(owners) if len(owners) == 1 else []

    def _mro(self, mod: str, cls: str) -> List[str]:
        out, queue, seen = [], [cls], set()
        while queue:
            c = queue.pop(0)
            if c in seen:
                continue
            seen.add(c)
            out.append(c)
            queue.extend(
                b.split(".")[-1]
                for b in self.class_info.get((mod, c), ())
            )
        return out

    def _resolve_all_calls(self) -> None:
        for gfid, rec in self.funcs.items():
            resolved = []
            for held, refs, line in rec["calls"]:
                callees: List[str] = []
                for ref in refs:
                    callees.extend(self._resolve_ref(gfid, ref))
                if callees:
                    resolved.append((held, callees, line))
            self._resolved_calls[gfid] = resolved

    # -- transitive acquisitions ------------------------------------------
    def _fixpoint_acquires(self) -> Dict[str, Dict[str, List[str]]]:
        acq: Dict[str, Dict[str, List[str]]] = {}
        for gfid, rec in self.funcs.items():
            path = self.func_path[gfid]
            mine: Dict[str, List[str]] = {}
            for sid, line in rec["direct"]:
                c = self.canon(sid)
                mine.setdefault(
                    c, [f"{gfid} acquires `{c}` at {path}:{line}"]
                )
            acq[gfid] = mine
        for _ in range(50):
            changed = False
            for gfid in self.funcs:
                path = self.func_path[gfid]
                mine = acq[gfid]
                for _held, callees, line in self._resolved_calls[gfid]:
                    for callee in callees:
                        for sid, chain in acq.get(callee, {}).items():
                            if sid in mine or len(chain) >= _MAX_WITNESS:
                                continue
                            mine[sid] = [
                                f"{gfid} calls {callee} at {path}:{line}"
                            ] + chain
                            changed = True
            if not changed:
                break
        return acq

    # -- the checks --------------------------------------------------------
    def findings(self) -> List[Finding]:
        # every distinct acquisition SITE of a (outer, inner) pair is its
        # own witness: a rank inversion is reported (and waived) per
        # site, exactly like the per-call lock-discipline findings — one
        # arbitrary witness per pair would leave sibling sites silently
        # unreviewed
        edges: Dict[Tuple[str, str], List[Tuple[str, int, List[str]]]] = {}

        def add_edge(
            outer: str, inner: str, path: str, line: int, chain: List[str]
        ) -> None:
            sites = edges.setdefault((outer, inner), [])
            if not any(p == path and l == line for p, l, _c in sites):
                sites.append((path, line, chain))

        for gfid in sorted(self.funcs):
            rec = self.funcs[gfid]
            path = self.func_path[gfid]
            for outer, inner, line in rec["edges"]:
                add_edge(self.canon(outer), self.canon(inner), path, line, [])
            for held, callees, line in self._resolved_calls[gfid]:
                if not held:
                    continue
                for callee in callees:
                    for sid, chain in self._acq.get(callee, {}).items():
                        for h in held:
                            add_edge(
                                self.canon(h), sid, path, line, chain
                            )

        out: List[Finding] = []
        for (outer, inner) in sorted(edges):
            for path, line, chain in edges[(outer, inner)]:
                via = (
                    " [via " + " ; ".join(chain) + "]" if chain else ""
                )
                if outer == inner:
                    if self._kind(outer) == "lock":
                        out.append(
                            Finding(
                                path, line, 0, "lock-order",
                                f"non-reentrant lock `{outer}` acquired "
                                "while already held by this thread — "
                                "guaranteed self-deadlock on first "
                                "execution (make it an RLock or split "
                                "the critical section)" + via,
                            )
                        )
                    continue
                r_out, r_in = self._rank(outer), self._rank(inner)
                if r_out is not None and r_in is not None and r_in > r_out:
                    out.append(
                        Finding(
                            path, line, 0, "lock-order",
                            f"rank inversion: `{inner}` "
                            f"({rank_name(r_in)}) acquired while holding "
                            f"`{outer}` ({rank_name(r_out)}) — the "
                            f"declared hierarchy ({table()}) requires "
                            "DESCENDING rank order; re-order the "
                            "acquisitions or waive with a reviewed "
                            "`# pathway: allow(lock-order): <rank "
                            "exception>`" + via,
                        )
                    )

        first_witness = {
            key: sites[0] for key, sites in edges.items()
        }
        out.extend(self._cycle_findings(first_witness))
        return out

    def _cycle_findings(
        self, edges: Dict[Tuple[str, str], Tuple[str, int, List[str]]]
    ) -> List[Finding]:
        graph: Dict[str, List[str]] = {}
        for (outer, inner) in edges:
            if outer != inner:
                graph.setdefault(outer, []).append(inner)
        for succs in graph.values():
            succs.sort()
        out: List[Finding] = []
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            cycle = _find_cycle(graph, scc)
            if not cycle:
                continue
            hops = []
            for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                path, line, _chain = edges[(a, b)]
                hops.append(f"`{a}` → `{b}` ({path}:{line})")
            first = edges[(cycle[0], cycle[1] if len(cycle) > 1 else cycle[0])]
            out.append(
                Finding(
                    first[0], first[1], 0, "lock-order",
                    "deadlock cycle in the observed acquisition graph — "
                    "two threads taking this loop from different entry "
                    "points deadlock; witness path: " + " ; ".join(hops),
                )
            )
        return out


def module_lock_sites(
    real_path: str, display_path: Optional[str] = None
) -> Dict[int, Tuple[str, str]]:
    """``{creation_line: (site_id, kind)}`` for every lock site in one
    module — the runtime sanitizer's naming table.  Both sides share
    THIS discovery, so a runtime edge names the same ``module.Class.attr``
    identity the static graph uses (the dynamic oracle can confirm or
    refute specific static edges)."""
    try:
        with open(real_path, "r", encoding="utf-8") as fh:
            source = fh.read()
        ctx = ModuleContext(real_path, display_path or real_path, source)
    except (OSError, SyntaxError, ValueError):
        return {}
    extractor = _Extractor(ctx, "lock-order")
    return {
        info["line"]: (sid, info["kind"])
        for sid, info in extractor.sites.items()
    }


def _sccs(graph: Dict[str, List[str]]) -> List[List[str]]:
    """Tarjan, iterative, deterministic (sorted node order)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    nodes = sorted(set(graph) | {v for vs in graph.values() for v in vs})

    for root in nodes:
        if root in index:
            continue
        work = [(root, 0)]
        while work:
            node, i = work.pop()
            if i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            succs = graph.get(node, ())
            for j in range(i, len(succs)):
                w = succs[j]
                if w not in index:
                    work.append((node, j + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(sorted(scc))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


def _find_cycle(
    graph: Dict[str, List[str]], scc: List[str]
) -> List[str]:
    """One concrete cycle inside an SCC (DFS from its smallest node)."""
    members = set(scc)
    start = scc[0]
    path: List[str] = [start]
    seen = {start}

    def dfs(node: str) -> Optional[List[str]]:
        for succ in graph.get(node, ()):
            if succ not in members:
                continue
            if succ == start:
                return list(path)
            if succ in seen:
                continue
            seen.add(succ)
            path.append(succ)
            got = dfs(succ)
            if got is not None:
                return got
            path.pop()
        return None

    return dfs(start) or []
