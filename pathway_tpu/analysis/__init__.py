"""Hot-path static analysis for pathway_tpu.

An AST lint framework plus five rule families that make the round-5 bug
classes (and the deadlock class) impossible to reintroduce silently:

- ``lock-discipline`` — device dispatch / host sync / GIL-holding C calls
  lexically inside ``with <lock>:`` bodies (the ``ops/ivf.py``
  absorb-under-lock and ``parallel/exchange.py`` pickle-starved-heartbeat
  class);
- ``hidden-sync`` — implicit host round trips on serve-path modules,
  cross-checked against the ``ops/dispatch_counter.py`` budget;
- ``recompile-hazard`` — jitted calls fed unbucketed Python-varying
  shapes (paired with the runtime tripwire in ``ops/recompile_guard.py``);
- ``lock-order`` — the whole-program concurrency sanitizer
  (``lock_order.py`` + ``lock_ranks.py``): lock-acquisition hierarchy
  inversions, deadlock cycles with witness paths, ``Condition.wait``
  holding a second lock, locks in jitted scopes — paired with the
  runtime tripwire in ``sanitizer.py`` (``PATHWAY_LOCK_SANITIZER=1``);
- ``value-flow`` — the device value-flow analyzer (``value_flow.py`` +
  ``residency.py``): use-after-donate on ``donate_argnums`` buffers,
  hidden host transfers (implicit ``bool``/iteration/``tolist``/
  comparison syncs), redundant loop-invariant uploads — paired with
  the runtime donation tripwire in ``ops/donation_guard.py``
  (``PATHWAY_DONATION_GUARD=1``).

Run ``python -m pathway_tpu.analysis pathway_tpu/`` for file:line
diagnostics (``--format sarif`` for CI diff annotation,
``--check-pragmas`` for stale-waiver audit, ``PATHWAY_ANALYSIS_CACHE``
for incremental repo-wide runs); suppress a reviewed finding in place
with ``# pathway: allow(<rule>): <reason>``.  The tier-1 gate
(``tests/test_analysis.py``) asserts the whole tree stays clean.
"""

from .core import (
    Finding,
    ModuleContext,
    Rule,
    analyze_file,
    analyze_paths,
    analyze_source,
    default_rules,
    iter_py_files,
    stale_pragma_findings,
)
from .hidden_sync import HiddenSyncRule
from .lock_discipline import LockDisciplineRule
from .lock_order import LockOrderRule
from .recompile_hazard import RecompileHazardRule
from .value_flow import ValueFlowRule

__all__ = [
    "Finding",
    "HiddenSyncRule",
    "LockDisciplineRule",
    "LockOrderRule",
    "ModuleContext",
    "RecompileHazardRule",
    "Rule",
    "ValueFlowRule",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "default_rules",
    "iter_py_files",
    "main",
    "stale_pragma_findings",
]


def main(argv=None) -> int:
    from .__main__ import main as _main

    return _main(argv)
