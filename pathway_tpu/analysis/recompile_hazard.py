"""recompile-hazard: jitted calls fed unbucketed Python-varying shapes.

XLA compiles one executable per distinct argument shape.  A jitted call
whose input shape tracks raw Python data (``len(texts)``, a tail that
grew by one row, an unpadded last chunk) recompiles on every new size —
seconds of XLA time on a latency path that budgets milliseconds.  The
repo-wide discipline is to bucket every host-fed dimension
(``_bucket``/``seg_bucket``/``row_length_bucket``/``pad_packed_rows``)
so each callable compiles a small closed set of signatures.

Lexical check, per function scope: a call to a jitted function with a
``jnp.asarray(...)``/``jnp.array(...)``-converted argument (host data
uploaded at call time — the shape comes from Python-land) in a scope
that never invokes a bucketing helper is flagged.  Scopes that bucket
anywhere cover all their dispatches: the helpers normalize every shape
they touch, and finer data-flow than that is beyond a lexical pass.

The static rule is paired with a runtime tripwire
(``ops/recompile_guard.py``): every compiled-fn cache in the serving
stack counts its distinct signatures and trips past a bound — so a
hazard that slips past the lexical pass still fails loudly under tests
instead of silently recompiling in production.
"""

from __future__ import annotations

import ast
from typing import Set

from .core import ModuleContext, Rule
from .registry import dotted_name, is_jit_call, scope_jit_and_device_vars, walk_scope

__all__ = ["RecompileHazardRule"]

_UPLOAD_CALLS = {"jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array"}
_BUCKET_HELPERS = {"_bucket", "seg_bucket", "row_length_bucket", "pad_packed_rows"}


class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    salt_sources = ("recompile_hazard.py",)
    description = (
        "jitted call fed jnp.asarray(host data) in a scope with no shape "
        "bucketing — every distinct input size compiles a new executable"
    )

    def run(self, ctx: ModuleContext) -> None:
        self._visit_scope(ctx, ctx.tree, None, None)

    def _visit_scope(self, ctx, scope, inherited_fns, inherited_vars) -> None:
        jit_fns, device_vars = scope_jit_and_device_vars(
            scope, ctx.jit_names, inherited_fns, inherited_vars
        )
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._check_scope(ctx, scope, jit_fns)
        for child in ast.iter_child_nodes(scope):
            self._recurse_defs(ctx, child, jit_fns, device_vars)

    def _recurse_defs(self, ctx, node, fns, dvars) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._visit_scope(ctx, node, fns, dvars)
            return
        if isinstance(node, ast.Lambda):
            return
        for child in ast.iter_child_nodes(node):
            self._recurse_defs(ctx, child, fns, dvars)

    def _check_scope(self, ctx, scope, jit_fns: Set[str]) -> None:
        if scope.name in ctx.jit_names:
            return  # the jitted body itself: jnp.asarray there is traced
        buckets = False
        for node in walk_scope(scope):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                leaf = callee.rsplit(".", 1)[-1] if callee else ""
                if leaf in _BUCKET_HELPERS:
                    buckets = True
                    break
        if buckets:
            return
        for node in walk_scope(scope):
            if not isinstance(node, ast.Call) or not is_jit_call(node, jit_fns):
                continue
            for arg in node.args:
                if (
                    isinstance(arg, ast.Call)
                    and dotted_name(arg.func) in _UPLOAD_CALLS
                ):
                    callee = dotted_name(node.func)
                    ctx.report(
                        self.name, node,
                        f"jitted `{callee}(...)` takes "
                        f"`{dotted_name(arg.func)}(host data)` but the "
                        "scope never buckets shapes — every distinct "
                        "input size recompiles (bucket with _bucket/"
                        "seg_bucket/row_length_bucket/pad_packed_rows, "
                        "or pad to a fixed shape and suppress with the "
                        "reason)",
                    )
                    break
