"""The residency lattice and the per-site donation registry.

The value-flow family (``value_flow.py``) tracks, per value, WHERE its
bytes currently live and whether they are still valid:

    HOST < DEVICE < DONATED

- ``HOST`` — a plain Python/NumPy value; touching it is free;
- ``DEVICE`` — the result of a jitted dispatch, a compiled-fn cache
  getter, a ``retry_call``/``profile.wrap`` wrapper, or an encoder
  ``.encode(...)`` call: still unfetched, so any host coercion is a
  blocking device→host transfer that must be booked (``record_fetch``);
- ``DONATED`` — the value was passed at a ``donate_argnums`` position of
  a donating jitted callable: XLA reused its buffer for the outputs, so
  the reference now points at garbage (jax marks it deleted) — ANY
  further read, fetch, or re-dispatch is a use-after-donate bug.

The rule classifies expressions to HOST/DEVICE
(``value_flow._Extractor._residency_of``); the DONATED state is
tracked per NAME by the finalize replay's poison map (poison at the
donating call, clear on rebind).  This module is pure data + tiny
helpers (no jax import) so the lint runs anywhere; the runtime twin
(``ops/donation_guard.py``) enforces the same DONATED transitions
dynamically under ``PATHWAY_DONATION_GUARD=1``.
"""

from __future__ import annotations

from typing import Dict, Tuple

__all__ = [
    "DECLARED_TRANSFERS",
    "DONATED",
    "DEVICE",
    "DONATION_SITES",
    "HOST",
    "declared_transfers_for",
]

# the lattice, ordered by danger: HOST(0) < DEVICE(1) < DONATED(2)
HOST = 0
DEVICE = 1
DONATED = 2


# -- per-site donation registry -------------------------------------------
#
# Every compiled callable in the tree that DONATES argument buffers,
# keyed by its program-unique leaf name, mapped to the donated
# positional indices.  Seeded from the real donation sites so a
# CROSS-module call (``ivf._absorb_scatter(...)`` through an import
# alias, or a helper reached by leaf name) resolves even when the
# defining module's AST is not in the analyzed set; module-local
# ``@partial(jax.jit, donate_argnums=...)`` defs are discovered from
# the AST and merged on top (``registry.collect_donating_jits``).
#
# Adding a donating callable to the serve stack means adding it HERE
# (or spelling it with an in-module donate_argnums the walker can see)
# — a donation the registry cannot name is a donation the
# use-after-donate check cannot police.
DONATION_SITES: Dict[str, Tuple[int, ...]] = {
    # ops/ivf.py — IVF absorb commit: scatters tail rows into free slab
    # slots; slabs + bias donated so the GB-scale update is in place
    "_absorb_scatter": (0, 1),
    # index/forward.py — forward-index absorb commit: scatters one
    # bucketed plan into the token/scale/nvalid row buckets, all three
    # donated
    "_forward_scatter": (0, 1, 2),
}


# -- declared deliberate transfers ----------------------------------------
#
# The static mirror of the in-code ``# pathway: allow(value-flow)``
# pragmas, exactly like ``lock_ranks.DECLARED_EXCEPTIONS`` mirrors the
# lock-order waivers: every DELIBERATE host↔device crossing the
# value-flow rule flags gets (a) a reviewed pragma at the site and (b)
# an entry here naming module, function and why the crossing is sound.
# ``tests/test_analysis.py`` gates the mirror in both directions — a
# pragma without a table entry, or a table entry whose crossing was
# fixed/moved, fails the tree.  Keys: (display-path suffix, function
# qualname).
DECLARED_TRANSFERS: Dict[Tuple[str, str], str] = {
    ("stdlib/indexing/embedding_adapter.py", "EmbeddingIndexAdapter._embed"): (
        "ingest-side host materialization: the adapter's contract is "
        "host float32 rows for the inner index, one batched crossing "
        "per micro-batch, off every serve lock"
    ),
    ("xpacks/llm/embedders.py", "TpuEmbedder.__init__.embed"): (
        "the embedder xpack's UDF contract is a host ndarray: one "
        "batched synchronous fetch per ingest micro-batch, never "
        "inside a serve stage"
    ),
    ("ops/serving.py", "FusedEncodeSearch._submit_sharded"): (
        "deliberate per-shard d2d scatter: the SAME embedding is placed "
        "on each shard's device once per serve — the transfer varies by "
        "TARGET device, not by value, so there is nothing to hoist"
    ),
    ("models/clip.py", "ClipModel.encode_text"): (
        "the sync model API: encode_text returns host rows by contract; "
        "serving pipelines submit/complete instead"
    ),
    ("models/clip.py", "ClipModel.encode_image"): (
        "the sync model API: encode_image returns host rows by contract"
    ),
    ("ops/ivf.py", "_kmeans"): (
        "k-means training loop: one synchronous assignment fetch per "
        "iteration is the trainer's contract, build-time only"
    ),
    ("ops/ivf.py", "IvfKnnIndex._layout_from_data"): (
        "slab layout build: chunked synchronous preference fetches, "
        "build/retrain-time only"
    ),
    ("ops/ivf.py", "IvfKnnIndex._plan_absorb"): (
        "absorb plan phase: one synchronous preference fetch on the "
        "off-lock background planner"
    ),
    ("ops/ivf.py", "IvfKnnIndex.build_from_matrix"): (
        "bulk build: chunked synchronous preference fetches, never on "
        "the serve path"
    ),
    ("ops/ivf.py", "IvfKnnIndex.search"): (
        "the reference host-search contract: synchronous results lists "
        "(serving books its crossings through submit/complete); the "
        "fetch runs off the index lock"
    ),
    ("serve/decode.py", "ContinuousDecoder._prefill_group"): (
        "the prefill JOIN's one deliberate host fetch: first tokens "
        "reach the riders' tickets before the step loop takes over"
    ),
    ("serve/decode.py", "ContinuousDecoder._step_chunk"): (
        "THE decode-loop fetch: one sync per step chunk delivers every "
        "slot's tokens (the int() below it reads the HOST copy — a "
        "name-level tracking limit, not a crossing)"
    ),
    ("serve/decode.py", "ContinuousDecoder._spec_round"): (
        "the speculative round's 2 deliberate fetches: draft proposals "
        "(host state seeding the verify's token operand) and the "
        "accepted-token matrix — the spec-flavor decode-loop sync, "
        "within the per-round 2-dispatch + 2-fetch budget"
    ),
    ("xpacks/llm/embedders.py", "SentenceTransformerEmbedder.__init__.embed"): (
        "SentenceTransformer is a host-side model: its .encode matches "
        "the device-producer spelling but returns numpy rows"
    ),
}


def declared_transfers_for(display_path: str) -> Dict[str, str]:
    """``{qualname: reason}`` for the declared deliberate crossings in
    one module (path suffix matched with separators normalised)."""
    path = display_path.replace("\\", "/")
    return {
        qual: reason
        for (suffix, qual), reason in DECLARED_TRANSFERS.items()
        if path.endswith(suffix)
    }
