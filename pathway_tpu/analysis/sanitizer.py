"""Runtime lock-order tripwire — the dynamic twin of ``lock_order.py``.

``PATHWAY_LOCK_SANITIZER=1`` (checked at ``pathway_tpu`` import) wraps
every lock CREATED by pathway code in an order-recording proxy:
``threading.Lock`` / ``RLock`` / ``Condition`` are replaced by factories
that inspect the creating frame — a creation inside the ``pathway_tpu``
package gets a proxy named by the SAME site discovery the static
analyzer uses (``lock_order.module_lock_sites``: the runtime edge
``serve.scheduler._CoalescerBase._qlock → observe.trace._store_lock``
names exactly the identity the static graph predicted, so live
interleavings confirm or refute specific static edges); everything else
(stdlib, jax, pytest internals) keeps the raw primitive at zero cost.

Per acquisition the proxy maintains:

- a **per-thread held stack** — what this thread holds, in order;
- a **global edge set** — every (held → acquired) site pair ever
  observed, with a **cycle check on each NEW edge** (DFS before the
  blocking acquire, so a planted ABBA deadlock raises instead of
  hanging);
- the **rank check** against ``lock_ranks``' declared hierarchy
  (descending order; ``DECLARED_EXCEPTIONS`` mirrors the reviewed
  ``allow(lock-order)`` pragmas);
- ``Condition.wait`` **while holding a second lock** detection;
- a **held-too-long watchdog**: ``PATHWAY_LOCK_HOLD_MS=<ms>`` counts a
  violation when a lock is held past the budget (count-only — wall
  timing is too noisy for a hard failure on shared CI boxes).

Violation policy: **raise under pytest** (``LockOrderViolation``; the
planted-deadlock fixture must fail loudly, not flake), **log + count in
prod** — ``pathway_sanitizer_violations_total{kind}`` on the scrape
surface, kinds ``rank-inversion`` / ``cycle`` / ``self-deadlock`` /
``wait-holding-lock`` / ``held-too-long``.  ``PATHWAY_LOCK_SANITIZER_RAISE``
overrides (1=always raise, 0=never).

This module is pure stdlib (no jax, no pathway imports at module scope)
so ``install()`` can run at the very top of ``pathway_tpu/__init__``
before any pathway module creates its locks.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderViolation",
    "enabled_from_env",
    "install",
    "installed",
    "make_lock",
    "reset",
    "stats",
    "uninstall",
    "violations",
]

_log = logging.getLogger("pathway_tpu.sanitizer")

VIOLATION_KINDS = (
    "rank-inversion", "cycle", "self-deadlock", "wait-holding-lock",
    "held-too-long",
)


class LockOrderViolation(RuntimeError):
    """A lock-order rule broken at runtime (raised under pytest)."""


# originals captured at import: the factories and internal state must
# never recurse through themselves
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_installed = False
_mutex = _ORIG_LOCK()          # guards the graph/edge/violation state
_tls = threading.local()       # .stack: List[_Held]
_seen_pairs: Set[Tuple[str, str]] = set()
_bad_pairs: Dict[Tuple[str, str], str] = {}  # pair -> violation kind
_graph: Dict[str, Set[str]] = {}
_violation_counts: Dict[str, int] = {k: 0 for k in VIOLATION_KINDS}
_logged: Set[str] = set()
_locks_tracked = 0
_site_tables: Dict[str, Dict[int, Tuple[str, str]]] = {}
_rank_cache: Dict[str, Optional[int]] = {}
_provider = None


def _hold_budget_ns() -> Optional[int]:
    ms = _config().get("analysis.lock_hold_ms")
    return int(ms * 1e6) if ms > 0 else None


def enabled_from_env() -> bool:
    return _config().get("analysis.lock_sanitizer")


def _should_raise() -> bool:
    return _config().get("analysis.lock_sanitizer_raise")


def _config():
    from .. import config

    return config


def _stack() -> List["_Held"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _Held:
    __slots__ = ("site", "inst", "rank", "t0_ns")

    def __init__(self, site: str, inst: int, rank: Optional[int]):
        self.site = site
        self.inst = inst
        self.rank = rank
        self.t0_ns = time.monotonic_ns()


# -- identity: shared with the static side --------------------------------
def _site_for_frame(filename: str, lineno: int) -> Tuple[str, Optional[int]]:
    """(site_id, rank) for a lock created at filename:lineno, named by
    the static analyzer's own site table for that module."""
    root_parent = os.path.dirname(_PKG_ROOT)
    rel = filename
    if filename.startswith(root_parent + os.sep):
        rel = os.path.relpath(filename, root_parent)
    table = _site_tables.get(filename)
    if table is None:
        from .lock_order import module_lock_sites

        table = _site_tables[filename] = module_lock_sites(filename, rel)
    rank = _rank_cache.get(filename)
    if filename not in _rank_cache:
        from .lock_ranks import rank_of_path

        rank = _rank_cache[filename] = rank_of_path(filename)
    entry = table.get(lineno)
    if entry is not None:
        return entry[0], rank
    # a creation the static table does not name (local variable, helper
    # factory): stable repo-relative module:line identity (NOT the
    # absolute path — ids must match across checkouts), module rank
    # still applies
    from .lock_order import module_dotted

    return f"{module_dotted(rel)}:{lineno}", rank


# -- violation recording ----------------------------------------------------
def _record_violation(
    kind: str, message: str, raise_ok: bool = True, detail: str = ""
) -> None:
    """``message`` must be STABLE per violation site (it is the log-dedup
    key and lives in a process-lifetime set); per-occurrence numbers go
    in ``detail``, which is logged but never keyed."""
    with _mutex:
        _violation_counts[kind] = _violation_counts.get(kind, 0) + 1
        first = message not in _logged
        if first:
            _logged.add(message)
    if first:  # one log line per distinct message; the counter sees all
        _log.error("lock sanitizer [%s]: %s%s", kind, message, detail)
    if raise_ok and _should_raise():
        raise LockOrderViolation(f"[{kind}] {message}{detail}")


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src→dst in the observed edge graph (caller holds _mutex)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for succ in _graph.get(node, ()):
            if succ == dst:
                return path + [dst]
            if succ not in seen:
                seen.add(succ)
                stack.append((succ, path + [succ]))
    return None


def _check_acquire(site: str, inst: int, rank: Optional[int], kind: str) -> None:
    """Order checks BEFORE the blocking acquire — a detected deadlock
    raises instead of deadlocking.  Bookkeeping is committed BEFORE any
    raise, so a swallowed first raise (the robust ladder catches broad
    exceptions) still leaves the pair marked bad and every recurrence
    counted and re-raised."""
    stack = _stack()
    if not stack:
        return
    # same-instance re-entry: legal for RLock/Condition (recorded, not an
    # edge), a guaranteed self-deadlock for a plain Lock
    for held in stack:
        if held.inst == inst:
            if kind == "lock":
                _record_violation(
                    "self-deadlock",
                    f"non-reentrant lock `{site}` re-acquired by the "
                    "thread already holding it",
                )
            return
    from .lock_ranks import pair_waived, rank_name, table

    # rank check against EVERY held lock on EVERY acquire (the static
    # side records edges from every held lock — the runtime must not
    # narrow that to the top of the stack, or an inversion against a
    # deeper-held lock hides behind a known-good (top, new) pair).  The
    # clean-path cost is one integer scan over a 1–3 entry stack.
    if rank is not None:
        for h in stack:
            if (
                h.rank is not None
                and h.rank < rank
                and not pair_waived(h.rank, rank)
            ):
                with _mutex:
                    _bad_pairs.setdefault((h.site, site), "rank-inversion")
                _record_violation(
                    "rank-inversion",
                    f"`{site}` ({rank_name(rank)}) acquired while holding "
                    f"`{h.site}` ({rank_name(h.rank)}) — declared "
                    f"hierarchy ({table()}) requires descending rank order",
                )
                break
    top = stack[-1]
    pair = (top.site, site)
    if pair in _seen_pairs:
        if _bad_pairs.get(pair) == "cycle":
            # count every recurrence, raise again under pytest so the
            # offending test fails deterministically
            _record_violation(
                "cycle",
                f"`{site}` acquired while holding `{top.site}` "
                "(recurrence of a reported deadlock cycle)",
            )
        return
    with _mutex:
        fresh = pair not in _seen_pairs
        if fresh:
            _seen_pairs.add(pair)
    if not fresh:
        return  # raced another thread's first observation
    # cycle check on the new edge: does the reverse direction already
    # exist in the observed graph?  The pair is marked bad INSIDE the
    # mutex, before the violation can raise.
    with _mutex:
        cycle = _path_exists(site, top.site)
        if cycle is None:
            _graph.setdefault(top.site, set()).add(site)
        else:
            _bad_pairs[pair] = "cycle"
    if cycle is not None:
        witness = " → ".join(cycle + [cycle[0]] if cycle[-1] != site else cycle)
        _record_violation(
            "cycle",
            f"acquiring `{site}` while holding `{top.site}` closes a "
            f"cycle in the observed acquisition graph (reverse path: "
            f"{witness}) — two threads taking the loop from different "
            "entry points deadlock",
        )


def _on_acquired(site: str, inst: int, rank: Optional[int]) -> None:
    _stack().append(_Held(site, inst, rank))


def _on_release(inst: int) -> None:
    stack = _stack()
    budget = _hold_budget_ns()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i].inst == inst:
            held = stack.pop(i)
            if budget is not None:
                dt = time.monotonic_ns() - held.t0_ns
                if dt > budget:
                    _record_violation(
                        "held-too-long",
                        f"`{held.site}` held past the "
                        f"{budget / 1e6:.0f} ms budget",
                        raise_ok=False,
                        detail=f" ({dt / 1e6:.1f} ms this occurrence)",
                    )
            return


# -- the proxies ------------------------------------------------------------
class _SanLock:
    """Order-recording wrapper over a raw Lock/RLock.  Exposes the full
    lock protocol including the private Condition hooks
    (``_release_save`` / ``_acquire_restore`` / ``_is_owned``) so a
    ``threading.Condition`` built over it works unchanged."""

    __slots__ = ("_inner", "site", "kind", "rank")

    def __init__(self, inner: Any, site: str, kind: str, rank: Optional[int]):
        self._inner = inner
        self.site = site
        self.kind = kind
        self.rank = rank

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # try-acquires cannot deadlock and carry no ordering claim
            _check_acquire(self.site, id(self), self.rank, self.kind)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _on_acquired(self.site, id(self), self.rank)
        return got

    def release(self) -> None:
        _on_release(id(self))
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:  # RLock pre-3.12 has no locked()
            return self._is_owned()

    # Condition protocol ---------------------------------------------------
    def _release_save(self):
        _on_release(id(self))
        inner = self._inner
        save = getattr(inner, "_release_save", None)
        if save is not None:
            return save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = self._inner
        restore = getattr(inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            inner.acquire()
        # re-acquire after a wait re-establishes the hold WITHOUT a new
        # ordering claim (wait-holding-lock already policed the rest)
        _on_acquired(self.site, id(self), self.rank)

    def _is_owned(self) -> bool:
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        return any(h.inst == id(self) for h in _stack())

    def __repr__(self) -> str:
        return f"<SanLock {self.site} over {self._inner!r}>"


class _SanCondition(_ORIG_CONDITION):
    """``threading.Condition`` over a sanitized lock, with the
    wait-holding-a-second-lock tripwire."""

    def _check_wait(self) -> None:
        me = self._lock
        inst = id(me)
        others = sorted(
            {
                h.site
                for h in _stack()
                if h.inst != inst
            }
        )
        if others:
            site = getattr(me, "site", repr(me))
            _record_violation(
                "wait-holding-lock",
                f"Condition.wait on `{site}` while holding "
                f"{', '.join(others)} — wait releases only its own "
                "lock; every other held lock blocks its waiters for "
                "the whole wait",
            )

    def wait(self, timeout: Optional[float] = None):
        self._check_wait()
        return super().wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._check_wait()
        return super().wait_for(predicate, timeout)


# -- factories --------------------------------------------------------------
def _creation_site(depth: int = 2) -> Optional[Tuple[str, int]]:
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover - shallow stack
        return None
    filename = frame.f_code.co_filename
    if not filename.startswith(_PKG_ROOT + os.sep):
        return None
    if os.sep + "analysis" + os.sep in filename:
        return None  # never wrap the analyzer/sanitizer's own locks
    return filename, frame.f_lineno


def _wrap(inner: Any, kind: str, where: Tuple[str, int]) -> _SanLock:
    global _locks_tracked
    site, rank = _site_for_frame(*where)
    with _mutex:
        _locks_tracked += 1
    return _SanLock(inner, site, kind, rank)


def _lock_factory():
    where = _creation_site()
    inner = _ORIG_LOCK()
    if where is None:
        return inner
    return _wrap(inner, "lock", where)


def _rlock_factory():
    where = _creation_site()
    inner = _ORIG_RLOCK()
    if where is None:
        return inner
    return _wrap(inner, "rlock", where)


def _condition_factory(lock: Any = None):
    where = _creation_site()
    if where is None:
        return _ORIG_CONDITION(lock)
    if lock is None:
        # Condition() owns a fresh RLock: track it under the condition's
        # own creation site
        lock = _wrap(_ORIG_RLOCK(), "rlock", where)
    return _SanCondition(lock)


def make_lock(
    name: str, kind: str = "lock", rank: Optional[int] = None
) -> _SanLock:
    """Explicitly tracked lock for tests/fixtures (the planted-deadlock
    pair): named and ranked regardless of where it is created."""
    global _locks_tracked
    inner = _ORIG_RLOCK() if kind == "rlock" else _ORIG_LOCK()
    with _mutex:
        _locks_tracked += 1
    return _SanLock(inner, name, kind, rank)


# -- install / observe -------------------------------------------------------
def install() -> bool:
    """Patch the threading lock constructors (idempotent).  Returns True
    when the sanitizer is active after the call."""
    global _installed
    if _installed:
        return True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _installed = True
    _ensure_provider()
    return True


def uninstall() -> None:
    """Restore the raw constructors.  Already-wrapped locks keep their
    proxies (they are plain objects); new creations go raw."""
    global _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    threading.Condition = _ORIG_CONDITION
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Clear edges/violations (tests, bench A/B arms).  Held stacks are
    per-thread and drain naturally."""
    with _mutex:
        _seen_pairs.clear()
        _bad_pairs.clear()
        _graph.clear()
        _logged.clear()
        for k in list(_violation_counts):
            _violation_counts[k] = 0


def violations() -> Dict[str, int]:
    with _mutex:
        return dict(_violation_counts)


def stats() -> Dict[str, Any]:
    _ensure_provider()
    with _mutex:
        return {
            "installed": _installed,
            "locks_tracked": _locks_tracked,
            "edges_observed": sum(len(v) for v in _graph.values()),
            "violations": dict(_violation_counts),
        }


class _Provider:
    """Flight-recorder provider: the ``pathway_sanitizer_*`` families
    (registered once the observe stack is importable; every kind always
    renders so a zero stays visible on the scrape)."""

    def observe_metrics(self):
        with _mutex:
            counts = dict(_violation_counts)
            tracked = _locks_tracked
            edges = sum(len(v) for v in _graph.values())
        for kind in VIOLATION_KINDS:
            yield (
                "counter",
                "pathway_sanitizer_violations_total",
                {"kind": kind},
                counts.get(kind, 0),
            )
        yield ("gauge", "pathway_sanitizer_locks_tracked", {}, tracked)
        yield ("gauge", "pathway_sanitizer_edges_observed", {}, edges)


def _ensure_provider() -> None:
    """Register the metrics provider when the observe stack is ready.
    At ``pathway_tpu/__init__`` time (install runs FIRST, before the
    package finishes importing) observe is not importable yet — retried
    from ``stats()`` and the first violation."""
    global _provider
    if _provider is not None:
        return
    try:
        from ..observe import register_provider
    except Exception:
        return
    _provider = _Provider()
    register_provider(_provider)
