"""AST lint framework for the serving hot path.

Round 5 shipped fixes for two instances of the same latent bug class —
device work and GIL-holding C calls executed under a lock (`ops/ivf.py`
absorb-under-lock, `parallel/exchange.py` pickle-starved heartbeat) — and
the serve path's "2 dispatches + 2 fetches" budget is guarded only at
runtime by `ops/dispatch_counter.py`.  This package detects those bug
classes statically, repo-wide, on every tier-1 run, so they cannot be
reintroduced silently.

Framework pieces (rules live in sibling modules):

- ``Finding`` — one diagnostic with ``path:line:col`` and a rule name;
- pragma suppression — ``# pathway: allow(<rule>[, <rule>]): <reason>``
  on (or covering) the offending line silences a finding WITH a recorded
  reason.  A pragma on the first line of a compound statement (``with``,
  ``for``, ``def``…) covers the whole statement body, so one reviewed
  reason can bless an entire lock section.  ``# pathway: allow-file(...)``
  covers the module.  Reasons are mandatory: a pragma without one is
  itself reported;
- ``# pathway: serve-path`` — marks a module as serve-path so the
  hidden-sync rule applies to it (a default list covers the known serving
  modules even without the marker);
- ``analyze_paths`` / ``analyze_file`` — the repo walker used by both the
  CLI (``python -m pathway_tpu.analysis``) and the tier-1 gate test.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "ModuleContext",
    "Rule",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "default_rules",
    "iter_py_files",
]

_PRAGMA_RE = re.compile(
    r"#\s*pathway:\s*allow(?P<scope>-file)?\(\s*(?P<rules>[\w\-, ]+)\s*\)"
    r"\s*(?::\s*(?P<reason>\S.*?))?\s*$"
)
_SERVE_PATH_RE = re.compile(r"#\s*pathway:\s*serve-path\b")

# modules the hidden-sync rule covers even without an in-file marker
DEFAULT_SERVE_PATH_MODULES = (
    "ops/serving.py",
    "ops/retrieve_rerank.py",
    "models/encoder.py",
    "models/cross_encoder.py",
)


@dataclass
class Finding:
    """One diagnostic.  ``suppressed`` findings carry the pragma reason so
    the CLI can audit every allowance alongside the live violations."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False
    reason: Optional[str] = None

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


@dataclass
class _Pragma:
    line: int
    rules: Set[str]
    reason: Optional[str]
    whole_file: bool
    span: Tuple[int, int] = (0, 0)  # statement body the pragma covers


class ModuleContext:
    """Everything a rule needs about one parsed module."""

    def __init__(self, path: str, display_path: str, source: str):
        self.path = path
        self.display_path = display_path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.pragmas = _collect_pragmas(source)
        _attach_spans(self.pragmas, self.tree)
        self.serve_path = bool(_SERVE_PATH_RE.search(source)) or any(
            display_path.replace(os.sep, "/").endswith(m)
            for m in DEFAULT_SERVE_PATH_MODULES
        )
        from .registry import collect_jit_names

        self.jit_names = collect_jit_names(self.tree)
        self.findings: List[Finding] = []

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        suppressed, reason = self._suppression_for(rule, line)
        self.findings.append(
            Finding(
                self.display_path, line, col, rule, message,
                suppressed=suppressed, reason=reason,
            )
        )

    def _suppression_for(self, rule: str, line: int) -> Tuple[bool, Optional[str]]:
        for p in self.pragmas:
            if rule not in p.rules and "*" not in p.rules:
                continue
            if p.whole_file or p.line == line or p.span[0] <= line <= p.span[1]:
                return True, p.reason
        return False, None


class Rule:
    """Base rule: subclasses set ``name`` and implement ``run(ctx)``,
    reporting through ``ctx.report`` (suppression is applied centrally)."""

    name = "rule"
    description = ""

    def run(self, ctx: ModuleContext) -> None:  # pragma: no cover - interface
        raise NotImplementedError


def _collect_pragmas(source: str) -> List[_Pragma]:
    pragmas: List[_Pragma] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            pragmas.append(
                _Pragma(
                    line=tok.start[0],
                    rules=rules,
                    reason=m.group("reason"),
                    whole_file=bool(m.group("scope")),
                )
            )
    except tokenize.TokenError:  # unterminated strings etc: no pragmas then
        pass
    return pragmas


def _attach_spans(pragmas: List[_Pragma], tree: ast.Module) -> None:
    """A pragma on a statement's FIRST line covers the whole statement
    (multi-line calls, a ``with`` body, a whole ``def``); a pragma on a
    comment line of its own covers the statement starting on the NEXT
    line (the conventional lint-pragma placement)."""
    if not pragmas:
        return
    stmt_lines = {
        node.lineno for node in ast.walk(tree) if isinstance(node, ast.stmt)
    }
    by_line: Dict[int, List[_Pragma]] = {}
    for p in pragmas:
        by_line.setdefault(p.line, []).append(p)
        if p.line not in stmt_lines:
            # standalone-comment placement only: claim the next line.  A
            # TRAILING pragma must never leak onto the following statement
            # — an unreviewed violation added right below an allowance has
            # to stay visible to the gate.
            by_line.setdefault(p.line + 1, []).append(p)
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        for p in by_line.get(node.lineno, ()):
            end = getattr(node, "end_lineno", node.lineno)
            start = min(p.line, node.lineno)
            p.span = (start, max(p.span[1], end))


def default_rules() -> List[Rule]:
    from .hidden_sync import HiddenSyncRule
    from .lock_discipline import LockDisciplineRule
    from .recompile_hazard import RecompileHazardRule

    return [LockDisciplineRule(), HiddenSyncRule(), RecompileHazardRule()]


def analyze_file(
    path: str,
    rules: Optional[Sequence[Rule]] = None,
    display_path: Optional[str] = None,
) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return analyze_source(
        source, display_path or path, rules=rules, real_path=path
    )


def analyze_source(
    source: str,
    display_path: str,
    rules: Optional[Sequence[Rule]] = None,
    real_path: Optional[str] = None,
) -> List[Finding]:
    try:
        ctx = ModuleContext(real_path or display_path, display_path, source)
    except SyntaxError as exc:
        return [
            Finding(
                display_path, exc.lineno or 0, exc.offset or 0,
                "parse-error", f"could not parse: {exc.msg}",
            )
        ]
    for rule in rules if rules is not None else default_rules():
        rule.run(ctx)
    # a pragma with no reason is itself a violation: allowances must be
    # reviewable, and "because it complained" is not a review
    for p in ctx.pragmas:
        if p.reason is None:
            ctx.findings.append(
                Finding(
                    display_path, p.line, 0, "pragma-missing-reason",
                    "suppression pragma without a ': <reason>' — every "
                    "allowance must record why it is safe",
                )
            )
    ctx.findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return ctx.findings


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def analyze_paths(
    paths: Sequence[str], rules: Optional[Sequence[Rule]] = None
) -> List[Finding]:
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    base = os.getcwd()
    for file_path in iter_py_files(paths):
        display = os.path.relpath(file_path, base)
        if display.startswith(".."):
            display = file_path
        findings.extend(analyze_file(file_path, rules=rules, display_path=display))
    return findings
