"""AST lint framework for the serving hot path.

Round 5 shipped fixes for two instances of the same latent bug class —
device work and GIL-holding C calls executed under a lock (`ops/ivf.py`
absorb-under-lock, `parallel/exchange.py` pickle-starved heartbeat) — and
the serve path's "2 dispatches + 2 fetches" budget is guarded only at
runtime by `ops/dispatch_counter.py`.  This package detects those bug
classes statically, repo-wide, on every tier-1 run, so they cannot be
reintroduced silently.

Framework pieces (rules live in sibling modules):

- ``Finding`` — one diagnostic with ``path:line:col`` and a rule name;
- pragma suppression — ``# pathway: allow(<rule>[, <rule>]): <reason>``
  on (or covering) the offending line silences a finding WITH a recorded
  reason.  A pragma on the first line of a compound statement (``with``,
  ``for``, ``def``…) covers the whole statement body, so one reviewed
  reason can bless an entire lock section.  ``# pathway: allow-file(...)``
  covers the module.  Reasons are mandatory: a pragma without one is
  itself reported;
- ``# pathway: serve-path`` — marks a module as serve-path so the
  hidden-sync rule applies to it (a default list covers the known serving
  modules even without the marker);
- ``analyze_paths`` / ``analyze_file`` — the repo walker used by both the
  CLI (``python -m pathway_tpu.analysis``) and the tier-1 gate test.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "ModuleContext",
    "Rule",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "default_rules",
    "iter_py_files",
    "stale_pragma_findings",
]

_PRAGMA_RE = re.compile(
    r"#\s*pathway:\s*allow(?P<scope>-file)?\(\s*(?P<rules>[\w\-, ]+)\s*\)"
    r"\s*(?::\s*(?P<reason>\S.*?))?\s*$"
)
_SERVE_PATH_RE = re.compile(r"#\s*pathway:\s*serve-path\b")

# modules the hidden-sync rule covers even without an in-file marker
DEFAULT_SERVE_PATH_MODULES = (
    "ops/serving.py",
    "ops/retrieve_rerank.py",
    "models/encoder.py",
    "models/cross_encoder.py",
)


@dataclass
class Finding:
    """One diagnostic.  ``suppressed`` findings carry the pragma reason so
    the CLI can audit every allowance alongside the live violations."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False
    reason: Optional[str] = None

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


@dataclass
class _Pragma:
    line: int
    rules: Set[str]
    reason: Optional[str]
    whole_file: bool
    span: Tuple[int, int] = (0, 0)  # statement body the pragma covers
    used: bool = False  # matched at least one finding (stale-waiver audit)


class ModuleContext:
    """Everything a rule needs about one parsed module."""

    def __init__(self, path: str, display_path: str, source: str):
        self.path = path
        self.display_path = display_path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.pragmas = _collect_pragmas(source)
        _attach_spans(self.pragmas, self.tree)
        self.serve_path = bool(_SERVE_PATH_RE.search(source)) or any(
            display_path.replace(os.sep, "/").endswith(m)
            for m in DEFAULT_SERVE_PATH_MODULES
        )
        from .registry import collect_jit_names

        self.jit_names = collect_jit_names(self.tree)
        self.findings: List[Finding] = []

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        suppressed, reason = self._suppression_for(rule, line)
        self.findings.append(
            Finding(
                self.display_path, line, col, rule, message,
                suppressed=suppressed, reason=reason,
            )
        )

    def _suppression_for(self, rule: str, line: int) -> Tuple[bool, Optional[str]]:
        return _suppress_with(self.pragmas, rule, line)


def _suppress_with(
    pragmas: Sequence[_Pragma], rule: str, line: int
) -> Tuple[bool, Optional[str]]:
    """First pragma covering (rule, line) wins; the match is recorded on
    the pragma so ``--check-pragmas`` can flag waivers that no longer
    suppress anything."""
    for p in pragmas:
        if rule not in p.rules and "*" not in p.rules:
            continue
        if p.whole_file or p.line == line or p.span[0] <= line <= p.span[1]:
            p.used = True
            return True, p.reason
    return False, None


class Rule:
    """Base rule: subclasses set ``name`` and implement ``run(ctx)``,
    reporting through ``ctx.report`` (suppression is applied centrally).

    **Whole-program rules** (the lock-order and value-flow families)
    additionally define ``finalize() -> List[Finding]``: ``run``
    extracts a per-module summary, ``finalize`` is called ONCE after
    every module has been seen and returns cross-module findings
    (suppression is applied by the caller from each finding's own
    module's pragmas).  For the incremental cache they also define
    ``dump_summary(path) -> dict`` (JSON-able per-module facts) and
    ``load_summary(path, summary)`` (rehydrate a cache hit without
    re-parsing).

    ``salt_sources`` names the analyzer source files THIS family's
    results depend on (``core.py`` and ``registry.py`` are always
    included — they are shared resolution machinery).  The incremental
    cache salts each family's cached results with only those files, so
    editing (or ADDING) one family re-runs just that family on warm
    modules instead of cold-invalidating every other family's cached
    findings.  ``None`` (the conservative default for out-of-tree
    rules) salts with every ``.py`` in the analysis package."""

    name = "rule"
    description = ""
    salt_sources: Optional[Tuple[str, ...]] = None

    def run(self, ctx: ModuleContext) -> None:  # pragma: no cover - interface
        raise NotImplementedError


def _collect_pragmas(source: str) -> List[_Pragma]:
    pragmas: List[_Pragma] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            pragmas.append(
                _Pragma(
                    line=tok.start[0],
                    rules=rules,
                    reason=m.group("reason"),
                    whole_file=bool(m.group("scope")),
                )
            )
    except tokenize.TokenError:  # unterminated strings etc: no pragmas then
        pass
    return pragmas


def _attach_spans(pragmas: List[_Pragma], tree: ast.Module) -> None:
    """A pragma on a statement's FIRST line covers the whole statement
    (multi-line calls, a ``with`` body, a whole ``def``); a pragma on a
    comment line of its own covers the statement starting on the NEXT
    line (the conventional lint-pragma placement)."""
    if not pragmas:
        return
    stmt_lines = {
        node.lineno for node in ast.walk(tree) if isinstance(node, ast.stmt)
    }
    by_line: Dict[int, List[_Pragma]] = {}
    for p in pragmas:
        by_line.setdefault(p.line, []).append(p)
        if p.line not in stmt_lines:
            # standalone-comment placement only: claim the next line.  A
            # TRAILING pragma must never leak onto the following statement
            # — an unreviewed violation added right below an allowance has
            # to stay visible to the gate.
            by_line.setdefault(p.line + 1, []).append(p)
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        for p in by_line.get(node.lineno, ()):
            end = getattr(node, "end_lineno", node.lineno)
            start = min(p.line, node.lineno)
            p.span = (start, max(p.span[1], end))


def default_rules() -> List[Rule]:
    from .hidden_sync import HiddenSyncRule
    from .knob_discipline import KnobDisciplineRule
    from .lock_discipline import LockDisciplineRule
    from .lock_order import LockOrderRule
    from .recompile_hazard import RecompileHazardRule
    from .value_flow import ValueFlowRule

    return [
        LockDisciplineRule(),
        HiddenSyncRule(),
        RecompileHazardRule(),
        LockOrderRule(),
        ValueFlowRule(),
        KnobDisciplineRule(),
    ]


def _run_module(
    source: str,
    display_path: str,
    rules: Sequence[Rule],
    real_path: Optional[str] = None,
) -> Tuple[Optional[ModuleContext], List[Finding]]:
    """Parse + run the per-module side of every rule.  Whole-program
    findings (rule.finalize) are NOT included — the caller owns that."""
    try:
        ctx = ModuleContext(real_path or display_path, display_path, source)
    except SyntaxError as exc:
        return None, [
            Finding(
                display_path, exc.lineno or 0, exc.offset or 0,
                "parse-error", f"could not parse: {exc.msg}",
            )
        ]
    for rule in rules:
        rule.run(ctx)
    # a pragma with no reason is itself a violation: allowances must be
    # reviewable, and "because it complained" is not a review
    for p in ctx.pragmas:
        if p.reason is None:
            ctx.findings.append(
                Finding(
                    display_path, p.line, 0, "pragma-missing-reason",
                    "suppression pragma without a ': <reason>' — every "
                    "allowance must record why it is safe",
                )
            )
    return ctx, ctx.findings


def _finalize_rules(
    rules: Sequence[Rule], pragma_map: Dict[str, List[_Pragma]]
) -> List[Finding]:
    """Collect whole-program findings and apply each one's own module's
    pragma suppression (a waiver lives at the acquisition site it
    blesses, exactly like per-module findings)."""
    out: List[Finding] = []
    for rule in rules:
        finalize = getattr(rule, "finalize", None)
        if finalize is None:
            continue
        for f in finalize():
            suppressed, reason = _suppress_with(
                pragma_map.get(f.path, ()), f.rule, f.line
            )
            f.suppressed, f.reason = suppressed, reason
            out.append(f)
    return out


def analyze_file(
    path: str,
    rules: Optional[Sequence[Rule]] = None,
    display_path: Optional[str] = None,
) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return analyze_source(
        source, display_path or path, rules=rules, real_path=path
    )


def analyze_source(
    source: str,
    display_path: str,
    rules: Optional[Sequence[Rule]] = None,
    real_path: Optional[str] = None,
) -> List[Finding]:
    """Single-module entry (fixtures, one-file CLI runs): per-module
    rules plus the whole-program pass over just this module."""
    rules = list(rules) if rules is not None else default_rules()
    ctx, findings = _run_module(source, display_path, rules, real_path)
    if ctx is not None:
        findings.extend(
            _finalize_rules(rules, {display_path: ctx.pragmas})
        )
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


# -- incremental analysis cache -------------------------------------------
#
# PATHWAY_ANALYSIS_CACHE=<dir> keys one JSON record per module on a
# content hash salted with the SHARED analyzer machinery (core.py +
# registry.py) — the repo-wide tier-1 gate then re-parses only changed
# modules.  Within a record, each rule FAMILY's findings and module
# summary carry their own salt over just that family's sources
# (``Rule.salt_sources``): editing one family — or ADDING a new one —
# re-runs only that family on warm modules instead of cold-invalidating
# the other families' cached results.  Records carry the per-family
# findings, the pragma table (spans included — whole-program
# suppression needs them without re-parsing) and each whole-program
# rule's module summary, so warm runs produce bit-identical findings
# to cold ones.

# analyzer files every family depends on (parsing, pragma handling and
# the shared name-resolution registry live here)
_SHARED_SOURCES = ("core.py", "registry.py")

_SALT_CACHE: Dict[Tuple[str, ...], str] = {}


def _salt_of(files: Tuple[str, ...]) -> str:
    cached = _SALT_CACHE.get(files)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for name in files:
        path = os.path.join(pkg, name)
        h.update(name.encode())
        try:
            with open(path, "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"<missing>")
    _SALT_CACHE[files] = out = h.hexdigest()
    return out


def _shared_salt() -> str:
    return _salt_of(_SHARED_SOURCES)


def _family_salt(rule: Rule) -> str:
    sources = rule.salt_sources
    if sources is None:
        # conservative fallback: every .py in the package
        pkg = os.path.dirname(os.path.abspath(__file__))
        sources = tuple(
            sorted(n for n in os.listdir(pkg) if n.endswith(".py"))
        )
    return _salt_of(_SHARED_SOURCES + tuple(sources))


def _cache_dir() -> Optional[str]:
    from .. import config

    return config.get("analysis.cache_dir") or None


def _cache_key(display: str, source: bytes) -> str:
    h = hashlib.sha256()
    h.update(_shared_salt().encode())
    h.update(display.encode())
    h.update(b"\0")
    h.update(source)
    return h.hexdigest()


def _cache_load(cache_dir: str, key: str) -> Optional[dict]:
    try:
        with open(os.path.join(cache_dir, key + ".json")) as fh:
            record = json.load(fh)
        return record if record.get("v") == 2 else None
    except (OSError, ValueError):
        return None


def _cache_store(cache_dir: str, key: str, record: dict) -> None:
    # best effort: an unwritable cache degrades to a cold run, never an
    # analysis failure
    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = os.path.join(cache_dir, f".{key}.tmp.{os.getpid()}")
        with open(tmp, "w") as fh:
            json.dump(record, fh)
        os.replace(tmp, os.path.join(cache_dir, key + ".json"))
    except OSError:
        pass


def _pragma_to_json(p: _Pragma) -> dict:
    return {
        "line": p.line, "rules": sorted(p.rules), "reason": p.reason,
        "whole_file": p.whole_file, "span": list(p.span), "used": p.used,
    }


def _pragma_from_json(d: dict) -> _Pragma:
    return _Pragma(
        line=d["line"], rules=set(d["rules"]), reason=d["reason"],
        whole_file=d["whole_file"], span=tuple(d["span"]), used=d["used"],
    )


def _analyze_one(
    file_path: str,
    display: str,
    rules: Sequence[Rule],
    cache_dir: Optional[str],
) -> Tuple[List[Finding], List[_Pragma]]:
    """One module through the per-family cache: families whose salt
    matches reuse their cached findings + summary; the module is parsed
    (once) only when at least one family is missing or stale, and only
    THOSE families run on it."""
    with open(file_path, "rb") as fh:
        raw = fh.read()
    key = _cache_key(display, raw) if cache_dir else None
    record = _cache_load(cache_dir, key) if cache_dir else None
    fam_salts = {rule.name: _family_salt(rule) for rule in rules}
    families = dict(record["families"]) if record is not None else {}
    need = [
        rule
        for rule in rules
        if families.get(rule.name, {}).get("salt") != fam_salts[rule.name]
    ]
    if record is not None and not need:
        pragmas = [_pragma_from_json(p) for p in record["pragmas"]]
        base_findings = [Finding(**f) for f in record["base"]]
        for rule in rules:
            loader = getattr(rule, "load_summary", None)
            summary = families[rule.name].get("summary")
            if loader is not None and summary is not None:
                loader(display, summary)
        fresh_names: Set[str] = set()
    else:
        source = raw.decode("utf-8")
        ctx, run_findings = _run_module(
            source, display, need, real_path=file_path
        )
        pragmas = ctx.pragmas if ctx is not None else []
        fresh_names = {rule.name for rule in need}
        base_findings = [
            f for f in run_findings if f.rule not in fresh_names
        ]
        for rule in need:
            entry: dict = {
                "salt": fam_salts[rule.name],
                "findings": [
                    f.__dict__
                    for f in sorted(
                        (f for f in run_findings if f.rule == rule.name),
                        key=lambda f: (f.line, f.col),
                    )
                ],
                "summary": None,
            }
            dumper = getattr(rule, "dump_summary", None)
            if dumper is not None:
                entry["summary"] = dumper(display)
            families[rule.name] = entry
        # salt-valid families NOT re-run still need their summaries live
        for rule in rules:
            if rule.name in fresh_names:
                continue
            loader = getattr(rule, "load_summary", None)
            summary = families.get(rule.name, {}).get("summary")
            if loader is not None and summary is not None:
                loader(display, summary)
        if cache_dir:
            _cache_store(
                cache_dir, key,
                {
                    "v": 2,
                    "pragmas": [_pragma_to_json(p) for p in pragmas],
                    "base": [f.__dict__ for f in base_findings],
                    "families": families,
                },
            )
    module_findings = list(base_findings)
    for rule in rules:
        entry = families.get(rule.name)
        if entry is None:
            continue
        if rule.name in fresh_names:
            module_findings.extend(
                Finding(**f) for f in entry["findings"]
            )
        else:
            cached = [Finding(**f) for f in entry["findings"]]
            # cached findings did not pass through ctx.report this run:
            # replay the suppression match so the pragma `used` flags
            # (the --check-pragmas audit) stay identical to a cold run
            for f in cached:
                if f.suppressed:
                    _suppress_with(pragmas, f.rule, f.line)
            module_findings.extend(cached)
    module_findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return module_findings, pragmas


def analyze_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    return_pragmas: bool = False,
):
    """Repo walker used by the CLI and the tier-1 gate: per-module rules
    over every ``.py`` under ``paths``, then the whole-program passes
    (lock-order graph, value-flow donation replay) over all of them
    together.  With ``return_pragmas=True`` returns ``(findings,
    pragma_map)`` so the caller can audit stale waivers
    (``--check-pragmas``)."""
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    pragma_map: Dict[str, List[_Pragma]] = {}
    cache_dir = _cache_dir()
    base = os.getcwd()
    for file_path in iter_py_files(paths):
        display = os.path.relpath(file_path, base)
        if display.startswith(".."):
            display = file_path
        module_findings, pragmas = _analyze_one(
            file_path, display, rules, cache_dir
        )
        findings.extend(module_findings)
        pragma_map[display] = pragmas
    extra = _finalize_rules(rules, pragma_map)
    extra.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    findings.extend(extra)
    if return_pragmas:
        return findings, pragma_map
    return findings


def stale_pragma_findings(
    pragma_map: Dict[str, List[_Pragma]]
) -> List[Finding]:
    """``--check-pragmas``: every suppression pragma that matched ZERO
    findings is itself reported — a waiver that no longer waives
    anything is rot (the code it blessed moved or was fixed), and it
    would silently bless the NEXT violation added near it."""
    out: List[Finding] = []
    for path in sorted(pragma_map):
        for p in pragma_map[path]:
            if p.used or p.reason is None:
                # reasonless pragmas are already reported as
                # pragma-missing-reason; don't double-count them here
                continue
            rules = ", ".join(sorted(p.rules))
            out.append(
                Finding(
                    path, p.line, 0, "stale-pragma",
                    f"suppression pragma allow({rules}) no longer "
                    "suppresses any finding — the violation it waived "
                    "was fixed or moved; delete the pragma (reason was: "
                    f"{p.reason})",
                )
            )
    return out
