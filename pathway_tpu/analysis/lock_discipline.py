"""lock-discipline: no device work or GIL-holding C calls under a lock.

The round-5 bug class: ``ops/ivf.py`` ran a device matmul + host fetch
inside ``add()``'s lock section (every concurrent ``search``/``submit``
stalled for the whole absorb), and ``parallel/exchange.py`` held the GIL
in one multi-hundred-MB ``pickle.dumps`` so the heartbeat thread starved
and healthy peers were declared dead.  Both are invisible to tests that
don't race the exact schedule — but both are *lexically visible*: a call
with device-dispatch / host-sync / GIL-holding semantics sitting inside a
``with <lock>:`` body.

Flagged inside lock bodies (nested ``def``/``lambda`` bodies excluded —
they execute later, not under the lock):

- calls to jitted functions (module ``jax.jit``/``pjit`` registry +
  cache-getter convention — see ``registry.py``): a dispatch enqueues
  device work and can block in C on a full device queue;
- ``.block_until_ready()`` — an unbounded host sync;
- ``jax.device_put`` / ``jax.device_get`` — blocking transfers;
- ``np.asarray``/``np.array``/``float``/``int``/``.item()`` on a value
  produced by a jitted call — an implicit device→host sync;
- ``pickle.dumps`` / ``pickle.loads`` / ``Pickler.dump`` /
  ``Unpickler.load`` — one GIL-holding C call for the whole payload;
- completing a serve handle (``handle = <obj>.submit(...)`` then
  ``handle()`` / ``handle.result()`` / ``handle.advance()``) — the
  completion IS the host fetch.  The coalescing scheduler's
  future-handoff contract (serve/scheduler.py) is dispatch on the
  scheduler thread, fetch on the WAITER: blocking on a batch while
  holding the admission lock would stall every admitter for a full
  device round trip;
- serve-cache access (``<*_cache>.get/put/lookup/...`` — the
  pathway_tpu/cache tiers): a cache call takes the tier's own lock and
  fires the ``cache.get``/``cache.put`` chaos sites, which may delay or
  HANG — under a serve lock the fault (or just the tier's contention)
  would stall every admitter instead of only the calling request.  The
  in-flight ownership pattern (persistence/object_cache.py
  ``get_or_compute``) is the sanctioned shape: the global lock guards
  only the owner dict; compute, backend I/O and pickling run off it;
- stream network I/O (``<stream|link|peer|conn>.send/.recv/
  .send_request`` — the fabric/exchange convention): a frame send can
  stall for a full heartbeat timeout on a congested peer and fires the
  ``fabric.send``/``fabric.recv`` chaos sites.  The sanctioned shape is
  serve/fabric.py's swap-under-lock / I/O-off-lock discipline.

And the INVERSE scope check on serve-path modules: a trace span opened
as a context manager (``with trace.span(...):`` / ``start_span`` /
``span_timer``) whose body ACQUIRES a lock.  Spans time *work*, not
lock waits — a span held across ``with <lock>:`` silently folds queue
contention into the stage it claims to measure, which is exactly the
mis-attribution per-request tracing exists to kill.  The serve paths
therefore record spans with EXPLICIT timestamps
(``trace.current().add_span(name, t0, t1)``), reusing the clock reads
the stage histograms already take.

Deliberate cases (e.g. a dispatch-only launch under the lock that
snapshots device state consistently and never blocks on the result) are
suppressed at the ``with`` statement with a reviewed reason:
``with self._lock:  # pathway: allow(lock-discipline): <why it is safe>``
"""

from __future__ import annotations

import ast
import re
from typing import Set

from .core import ModuleContext, Rule
from .registry import (
    dotted_name,
    is_cache_access,
    is_device_value_arg,
    is_device_value_base,
    is_handle_fetch,
    is_jit_call,
    is_lock_context,
    is_observability_callback,
    is_stream_io,
    scope_handle_vars,
    scope_jit_and_device_vars,
    walk_scope,
)

__all__ = ["LockDisciplineRule"]

_TRANSFER_CALLS = {
    "jax.device_put": "host→device transfer",
    "jax.device_get": "device→host sync",
}
_PICKLE_CALLS = {
    "pickle.dumps", "pickle.loads", "pickle.dump", "pickle.load",
    "marshal.dumps", "marshal.loads",
}
_COERCIONS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
              "float", "int"}
# span-opening context managers (observe/trace.py and OTLP-style APIs):
# `with trace.span(...)`, `with tracer.start_span(...)`, span timers
_SPAN_CM_LEAVES = {"span", "start_span", "span_timer"}


def _is_span_context(with_node: ast.With) -> bool:
    """``with <something>.span(...):`` / ``start_span`` / ``span_timer``
    — a context manager that TIMES its body as a trace span."""
    return _span_item_index(with_node) is not None


def _span_item_index(with_node: ast.With):
    """Index of the first span-opening item in the with statement, or
    None."""
    for i, item in enumerate(with_node.items):
        expr = item.context_expr
        if not isinstance(expr, ast.Call):
            continue
        callee = dotted_name(expr.func)
        if callee is None:
            continue
        if callee.rsplit(".", 1)[-1] in _SPAN_CM_LEAVES:
            return i
    return None


def _lock_item_index(with_node: ast.With):
    """Index of the first lock item in the with statement, or None."""
    for i, item in enumerate(with_node.items):
        name = dotted_name(item.context_expr)
        if name and _LOCK_ITEM_RE.search(name.rsplit(".", 1)[-1]):
            return i
    return None


# mirrors registry.is_lock_context's name heuristic, applied per item so
# the combined `with tracer.span(...), self._lock:` form resolves with
# ITEM ORDER (span before lock = the lock wait is timed)
_LOCK_ITEM_RE = re.compile(r"lock|mutex|cv\b|cond", re.IGNORECASE)


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    salt_sources = ("lock_discipline.py",)
    description = (
        "device dispatch / host sync / GIL-holding C call inside a "
        "`with <lock>:` body"
    )

    def run(self, ctx: ModuleContext) -> None:
        # map each function scope to its (jit callables, device vars,
        # serve handles), inheriting through closures so `with` bodies
        # resolve names bound by the enclosing function
        scope_envs = {}

        def visit_scope(scope, inherited_fns, inherited_vars, inherited_handles):
            fns, dvars = scope_jit_and_device_vars(
                scope, ctx.jit_names, inherited_fns, inherited_vars
            )
            handles = scope_handle_vars(scope, inherited_handles)
            scope_envs[scope] = (fns, dvars, handles)
            # walk_scope stops at nested defs; recurse into them explicitly
            # so closures inherit the enclosing scope's environment
            for child in ast.iter_child_nodes(scope):
                self._recurse_defs(child, fns, dvars, handles, visit_scope)

        visit_scope(ctx.tree, None, None, None)

        for scope, (jit_fns, device_vars, handles) in scope_envs.items():
            for node in walk_scope(scope):
                if isinstance(node, ast.With) and is_lock_context(node):
                    self._check_lock_body(ctx, node, jit_fns, device_vars, handles)

        # the inverse scope check (serve-path modules): a span context
        # manager whose body acquires a lock times the lock WAIT as if
        # it were stage work
        if ctx.serve_path:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.With) and _is_span_context(node):
                    self._check_span_body(ctx, node)

    def _check_span_body(self, ctx: ModuleContext, span_node: ast.With) -> None:
        message = (
            "trace span opened across a `with <lock>:` boundary on "
            "a serve-path module — spans time WORK, not lock waits; "
            "record the span with explicit timestamps "
            "(trace.current().add_span(name, t0, t1)) around the "
            "work itself, outside the lock acquisition"
        )
        # combined single-statement form: `with tracer.span(...),
        # self._lock:` acquires the lock INSIDE the span timing when the
        # span item comes first (`with self._lock, tracer.span(...)` is
        # the nested span-under-lock shape, which is allowed)
        span_i = _span_item_index(span_node)
        lock_i = _lock_item_index(span_node)
        if lock_i is not None and span_i is not None and span_i < lock_i:
            ctx.report(self.name, span_node, message)
            return
        for inner in walk_scope(span_node):
            if isinstance(inner, ast.With) and is_lock_context(inner):
                ctx.report(self.name, span_node, message)
                return

    def _recurse_defs(self, node, fns, dvars, handles, visit_scope) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_scope(node, fns, dvars, handles)
            return
        if isinstance(node, (ast.Lambda,)):
            return
        for child in ast.iter_child_nodes(node):
            self._recurse_defs(child, fns, dvars, handles, visit_scope)

    def _check_lock_body(
        self,
        ctx: ModuleContext,
        with_node: ast.With,
        jit_fns: Set[str],
        device_vars: Set[str],
        handle_vars: Set[str],
    ) -> None:
        for node in walk_scope(with_node):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            leaf = callee.rsplit(".", 1)[-1] if callee else ""
            if is_jit_call(node, jit_fns):
                ctx.report(
                    self.name, node,
                    f"jitted dispatch `{callee}(...)` under lock — device "
                    "work (and a possible C-level block on a full queue) "
                    "while every other thread waits on this lock",
                )
            elif leaf == "block_until_ready":
                ctx.report(
                    self.name, node,
                    f"`{callee}()` under lock — unbounded host sync while "
                    "holding the lock",
                )
            elif callee in _TRANSFER_CALLS:
                ctx.report(
                    self.name, node,
                    f"`{callee}` under lock — {_TRANSFER_CALLS[callee]} "
                    "blocks the lock for a full link round trip",
                )
            elif callee in _PICKLE_CALLS or leaf in ("dump", "load") and (
                callee or ""
            ).split(".", 1)[0].lower().find("pickl") >= 0:
                ctx.report(
                    self.name, node,
                    f"`{callee}` under lock — one GIL-holding C call for "
                    "the whole payload starves every other thread "
                    "(heartbeats included) for its duration",
                )
            elif callee in _COERCIONS and is_device_value_arg(
                node, jit_fns, device_vars
            ):
                ctx.report(
                    self.name, node,
                    f"`{callee}` of a jitted-call result under lock — "
                    "implicit device→host sync while holding the lock",
                )
            elif leaf == "item" and is_device_value_base(node, device_vars):
                ctx.report(
                    self.name, node,
                    "`.item()` on a jitted-call result under lock — "
                    "implicit device→host sync while holding the lock",
                )
            else:
                handle = is_handle_fetch(node, handle_vars)
                cache = is_cache_access(node)
                obs = is_observability_callback(node)
                stream = is_stream_io(node)
                if handle is not None:
                    ctx.report(
                        self.name, node,
                        f"serve handle `{handle}(...)` completed under lock "
                        "— the completion is the host fetch; the "
                        "future-handoff contract is dispatch on the "
                        "scheduler thread, fetch on the WAITER off-lock "
                        "(blocking here stalls every admitter)",
                    )
                elif cache is not None:
                    ctx.report(
                        self.name, node,
                        f"serve-cache access `{cache}(...)` under lock — "
                        "cache calls take the tier's own lock and fire "
                        "the cache.get/cache.put chaos sites (delay/hang);"
                        " keep lookups off the serve locks so a cache "
                        "fault wedges only its own request",
                    )
                elif obs is not None:
                    ctx.report(
                        self.name, node,
                        f"observability callback `{obs}(...)` under lock "
                        "— profiler/ledger/SLO sampling is pull-based by "
                        "design (walks weak registries, fires the "
                        "profile.sample/hbm.ledger/slo.evaluate chaos "
                        "sites, may delay or hang); it belongs on "
                        "scrape/bench threads, never inside a serve-path "
                        "lock where the walk stalls every admitter",
                    )
                elif stream is not None:
                    ctx.report(
                        self.name, node,
                        f"stream network I/O `{stream}(...)` under lock — "
                        "a frame send can stall for a full heartbeat "
                        "timeout on a congested peer and fires the "
                        "fabric.send/fabric.recv chaos sites (delay/hang);"
                        " swap the stream slot under the lock and perform "
                        "the I/O after releasing it (the fabric "
                        "mark_down/close discipline)",
                    )
