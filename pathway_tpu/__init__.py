"""pathway_tpu — a TPU-native live-data framework.

A from-scratch reimplementation of the capabilities of Pathway
(reference: /root/reference, v0.16.2 — incremental streaming dataflow with a
Python table API, connectors, persistence, and an LLM/RAG xpack), designed
for JAX/XLA on TPU: columnar micro-batch deltas, batched jit ML UDFs, and a
mesh-sharded live vector index (see SURVEY.md).

Usage mirrors the reference's ``import pathway as pw`` surface::

    import pathway_tpu as pw

    t = pw.debug.table_from_markdown(...)
    out = t.filter(pw.this.x > 0).groupby(pw.this.k).reduce(
        k=pw.this.k, s=pw.reducers.sum(pw.this.x))
    pw.debug.compute_and_print(out)
"""

from __future__ import annotations

# the runtime lock-order sanitizer must patch the threading constructors
# BEFORE any pathway module creates its locks — this import chain is
# where they all get created, so the hook runs first.  The knob registry
# is pure stdlib and import-cycle-free, so it loads before everything;
# the analysis package (six modules) loads only when the knob is ON.
from . import config

if config.get("analysis.lock_sanitizer"):
    from .analysis.sanitizer import install as _sanitizer_install

    _sanitizer_install()

from .internals import dtype as dt
from .internals import api_reducers as reducers
from .internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    CoalesceExpression,
    ColumnExpression,
    ColumnReference,
    IfElseExpression,
    MakeTupleExpression,
    RequireExpression,
)
from .internals.keys import Pointer, ref_scalar
from .internals.parse_graph import G
from .internals.run import run, run_all
from .internals.schema import (
    ColumnDefinition,
    Schema,
    SchemaProperties,
    column_definition,
    schema_builder,
    schema_from_csv,
    schema_from_dict,
    schema_from_types,
)
from .internals.table import (
    GroupedJoinResult,
    GroupedTable,
    Joinable,
    JoinMode,
    JoinResult,
    Table,
    TableLike,
    TableSlice,
)
from .internals.thisclass import left, right, this
from .internals.universe import Universe
from .internals.py_object_wrapper import PyObjectWrapper, wrap_py_object
from .internals.interactive import LiveTable, enable_interactive_mode

# submodules
from . import debug  # noqa: E402
from . import demo  # noqa: E402
from . import io  # noqa: E402
from . import universes  # noqa: E402
from .internals import udfs  # noqa: E402
from .internals.udfs import UDF, udf, udf_async  # noqa: E402
from .internals.yaml_loader import load_yaml  # noqa: E402
from .internals.export_import import ExportedTable, export_table, import_table  # noqa: E402
from .internals.sql import sql  # noqa: E402
from .internals.config import (  # noqa: E402
    PathwayConfig,
    get_config,
    set_license_key,
    set_monitoring_config,
)
from .internals.monitoring import MonitoringLevel  # noqa: E402
from .internals.api_reducers import BaseCustomAccumulator  # noqa: E402
from . import persistence  # noqa: E402
from .persistence import PersistenceMode  # noqa: E402
from . import parallel  # noqa: E402
from . import robust  # noqa: E402
from . import serve  # noqa: E402
from . import stdlib  # noqa: E402
from .stdlib import (  # noqa: E402
    graphs,
    indexing,
    ml,
    ordered,
    stateful,
    statistical,
    temporal,
    utils,
    viz,
)
from .stdlib.temporal import (  # noqa: E402
    AsofJoinResult,
    IntervalJoinResult,
    WindowJoinResult,
    asof_join,
    interval_join,
    window_join,
    windowby,
)
from .stdlib.utils.async_transformer import AsyncTransformer  # noqa: E402
from .stdlib.utils.pandas_transformer import pandas_transformer  # noqa: E402

# deprecated aliases kept for reference compatibility (pathway.asynchronous,
# UDFSync/UDFAsync pre-date the unified pw.UDF)
UDFSync = UDF
UDFAsync = UDF

__version__ = "0.1.0"


def reset() -> None:
    """Clear the global computation graph (fresh build)."""
    G.clear()
    from .internals.error_log import clear_error_log, reset_local_sinks

    clear_error_log()
    reset_local_sinks()
    from .internals.export_import import close_all_exports

    close_all_exports()
    from .internals.universe_solver import get_solver

    get_solver().clear()


def global_error_log() -> list:
    """Row-level errors recorded this run (reference pw.global_error_log —
    error-log table routing, src/engine/error.rs:337); see
    internals/error_log.py."""
    from .internals.error_log import global_error_log as _gel

    return _gel()


def local_error_log():
    """Context manager capturing errors raised while open (reference
    pw.local_error_log, internals/errors.py:13)."""
    from .internals.error_log import local_error_log as _lel

    return _lel()


# ---------------------------------------------------------------------------
# free functions of the pw.* namespace
# ---------------------------------------------------------------------------

def apply(fun, *args, **kwargs) -> ApplyExpression:
    """Row-wise python function application (reference pw.apply)."""
    return ApplyExpression(fun, None, args=args, kwargs=kwargs)


def apply_with_type(fun, ret_type, *args, **kwargs) -> ApplyExpression:
    return ApplyExpression(fun, ret_type, args=args, kwargs=kwargs)


def apply_async(fun, *args, **kwargs) -> AsyncApplyExpression:
    return AsyncApplyExpression(fun, None, args=args, kwargs=kwargs)


def if_else(if_clause, then_clause, else_clause) -> IfElseExpression:
    return IfElseExpression(if_clause, then_clause, else_clause)


def coalesce(*args) -> CoalesceExpression:
    return CoalesceExpression(*args)


def require(val, *args) -> RequireExpression:
    return RequireExpression(val, *args)


def make_tuple(*args) -> MakeTupleExpression:
    return MakeTupleExpression(*args)


def cast(target_type, expr):
    from .internals.expression import CastExpression

    return CastExpression(expr, target_type)


def declare_type(target_type, col):
    """Retype a column in the schema only; values pass through unchanged
    (reference internals/common.py:215)."""
    from .internals.expression import DeclareTypeExpression

    return DeclareTypeExpression(col, target_type)


def fill_error(col, replacement):
    """Replace Error cells with ``replacement`` per row (reference
    internals/common.py:438; Error cells: internals/error_value.py)."""
    from .internals.expression import FillErrorExpression

    return FillErrorExpression(col, replacement)


# free-function flavors of the Table/Joinable methods (reference
# internals/table.py:2574 `groupby`, internals/joins.py:1163 `join_inner` …)

def join(left_table, right_table, *on, id=None, how=JoinMode.INNER) -> JoinResult:
    return left_table.join(right_table, *on, id=id, how=how)


def join_inner(left_table, right_table, *on, id=None) -> JoinResult:
    return left_table.join_inner(right_table, *on, id=id)


def join_left(left_table, right_table, *on, id=None) -> JoinResult:
    return left_table.join_left(right_table, *on, id=id)


def join_right(left_table, right_table, *on, id=None) -> JoinResult:
    return left_table.join_right(right_table, *on, id=id)


def join_outer(left_table, right_table, *on, id=None) -> JoinResult:
    return left_table.join_outer(right_table, *on, id=id)


def groupby(grouped, *args, **kwargs):
    return grouped.groupby(*args, **kwargs)


def unwrap(expr):
    from .internals.expression import smart_coerce

    return smart_coerce(expr)


def assert_table_has_schema(table, schema, *, allow_superset=False) -> None:
    th = table.typehints()
    for name in schema.column_names():
        if name not in th:
            raise AssertionError(f"column {name} missing from table")
    if not allow_superset:
        extra = set(th) - set(schema.column_names())
        if extra:
            raise AssertionError(f"unexpected columns: {extra}")


def table_transformer(fn=None, **kwargs):
    """Decorator marking a Table→Table transformer (typing sugar)."""

    def wrap(f):
        return f

    return wrap(fn) if fn is not None else wrap


from .internals.iterate import iterate, iterate_universe  # noqa: E402
from .internals.row_transformer import (  # noqa: E402
    ClassArg,
    attribute,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)


# Heavy subpackages (flax model zoo, LLM xpack, device kernels) load lazily
# so plain ETL pipelines don't pay the model-stack import cost (PEP 562);
# `asynchronous` is lazy so its DeprecationWarning only fires on use.
_LAZY_SUBMODULES = ("xpacks", "models", "ops", "asynchronous")


def __getattr__(name: str):
    if name in _LAZY_SUBMODULES:
        import importlib

        module = importlib.import_module(f".{name}", __name__)
        globals()[name] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# Type aliases exposed like reference pw.* (DateTime*/Duration are plain
# datetime types — engine columns hold them natively, dtype.py:107-109)
import datetime as _datetime  # noqa: E402

Json = dt.JSON
Pointer_ = Pointer
DateTimeNaive = _datetime.datetime
DateTimeUtc = _datetime.datetime
Duration = _datetime.timedelta
# pw.Type — the reference's engine type vocabulary (engine.pyi:33)
Type = dt.PathwayType
# outer joins return a JoinResult here; the reference's docstrings call that
# an "OuterJoinResult object" (internals/joins.py:393) and its __all__ lists
# the name without ever defining it — alias for drop-in compat. (`window`,
# the other stale reference __all__ entry, is deliberately NOT provided.)
OuterJoinResult = JoinResult
