"""DEPRECATED alias of :mod:`pathway_tpu.internals.udfs`.

The reference keeps ``pathway.asynchronous`` as a deprecated re-export of the
``udfs`` helpers (reference python/pathway/asynchronous.py) for code written
against the pre-``pw.udfs`` API; same here.
"""

from __future__ import annotations

from warnings import warn

from .internals.udfs import (  # noqa: F401
    AsyncRetryStrategy,
    CacheStrategy,
    DefaultCache,
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    InMemoryCache,
    NoRetryStrategy,
    async_options,
    coerce_async,
    with_capacity,
    with_timeout,
)

warn(
    "pathway_tpu.asynchronous is deprecated; use pathway_tpu.udfs instead",
    DeprecationWarning,
    stacklevel=2,
)
