"""THE knob module: every ``PATHWAY_*`` environment knob, declared once.

Until round 18 the tree read ~75 raw ``os.environ`` sites spread over
50+ distinct ``PATHWAY_*`` names, with three incompatible bool
conventions (``not in ("0","false","off")`` default-on,
``in ("1","true","on")`` explicit-on, ``not in ("", "0")``), unvalidated
``int()``/``float()`` parses that raised ``ValueError`` mid-serve on a
poisoned env, and hot-path sites re-parsing per call.  This module is
the refactor ROADMAP item 6 names: one declarative registry —

- every knob declared ONCE with its dotted key, env name, type, typed
  default, parse, bounds, mutability class and a one-line doc;
- ``config.get("serve.coalesce_us")`` is a cached typed lookup: the
  parse runs only when the raw env string changes (one dict probe + one
  ``os.environ`` probe + a string compare on the hot path — priced by
  the ``self_tuning`` bench's config-lookup A/B at <1% p50);
- invalid values **clamp and log once** instead of raising: garbage
  falls back to the declared default, out-of-bounds numerics clamp to
  the declared ``[lo, hi]``, and the serve path never sees the
  ``ValueError`` the old inline ``float(os.environ.get(...))`` threw;
- mutability is part of the declaration: ``static`` knobs are read at
  startup and pinned (every knob a bit-identity parity oracle covers is
  static — quantization modes, speculation depth, cache-composition
  toggles); ``dynamic`` knobs may be adjusted ONLINE by the tuner
  (serve/tuner.py) through ``config.set``, always within the declared
  clamps.  ``set`` on a static knob raises ``StaticKnobError`` — the
  type system is the tuner veto.

Enforcement is the 6th analyzer family (analysis/knob_discipline.py):
any raw ``PATHWAY_*`` env read outside THIS file is a finding, as is an
undeclared knob reference or a declared-but-unread (dead) knob — the
tier-1 gate keeps the tree at zero.

``python -m pathway_tpu.config --format {text,json,markdown}`` renders
the full table; the README "Configuration" section embeds the markdown
form and a drift test gates the two against each other in both
directions, exactly like the metrics inventory.

Pure stdlib, no jax — the analysis package imports the registry and
must keep running on boxes with no accelerator stack.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "DYNAMIC",
    "STATIC",
    "Knob",
    "StaticKnobError",
    "UnknownKnobError",
    "clear_override",
    "clear_overrides",
    "describe",
    "get",
    "get_site",
    "knobs",
    "load",
    "markdown_table",
    "overrides",
    "registry",
    "set",
    "snapshot",
]

_log = logging.getLogger("pathway_tpu.config")

STATIC = "static"
DYNAMIC = "dynamic"

# the ONE bool convention (satellite: cache/store.py treated unset as on
# via `not in ("0","false","off")` while cache/embedding.py required an
# explicit `("1","true","on")` — both now parse through here, keeping
# each knob's DOCUMENTED default while unifying the accepted spellings)
_TRUE = ("1", "true", "yes", "on")
_FALSE = ("", "0", "false", "no", "off")


class StaticKnobError(TypeError):
    """``config.set`` on a ``static``-class knob: the declaration IS the
    tuner veto — bit-identity-pinned knobs can never move at runtime."""


class UnknownKnobError(KeyError):
    """A dotted key no declaration covers (the analyzer catches literal
    misspellings statically; this is the runtime twin)."""


@dataclass(frozen=True)
class Knob:
    """One declared knob.  ``kind`` drives the parse; ``lo``/``hi``
    clamp numerics; ``choices`` constrain enums; ``site_prefix`` marks a
    per-site env family (``PATHWAY_RETRY_ATTEMPTS_<SITE>``) resolved via
    ``get_site``; ``auto_pytest`` bools default to "on under pytest"
    when unset (the strict-mode tripwire convention) and are volatile
    (never cached — the pytest marker env changes per test)."""

    key: str
    env: str
    kind: str  # bool | int | float | str | enum
    default: Any
    doc: str
    lo: Optional[float] = None
    hi: Optional[float] = None
    choices: Optional[Tuple[str, ...]] = None
    mutability: str = STATIC
    site_prefix: Optional[str] = None
    auto_pytest: bool = False

    def default_doc(self) -> str:
        if self.auto_pytest:
            return "auto (on under pytest)"
        if self.kind == "bool":
            return "on" if self.default else "off"
        return str(self.default)


_REGISTRY: Dict[str, Knob] = {}
_BY_ENV: Dict[str, Knob] = {}
# key -> (raw env string seen at parse time, typed value)
_cache: Dict[str, Tuple[Optional[str], Any]] = {}
# tuner layer: key -> typed value (dynamic knobs only, always clamped)
_overrides: Dict[str, Any] = {}
_warned: set = set()
_lock = threading.Lock()


def _knob(
    key: str,
    env: str,
    kind: str,
    default: Any,
    doc: str,
    lo: Optional[float] = None,
    hi: Optional[float] = None,
    choices: Optional[Tuple[str, ...]] = None,
    mutability: str = STATIC,
    site_prefix: Optional[str] = None,
    auto_pytest: bool = False,
) -> None:
    k = Knob(
        key, env, kind, default, doc, lo=lo, hi=hi, choices=choices,
        mutability=mutability, site_prefix=site_prefix,
        auto_pytest=auto_pytest,
    )
    if key in _REGISTRY or env in _BY_ENV:
        raise ValueError(f"duplicate knob declaration: {key} / {env}")
    _REGISTRY[key] = k
    _BY_ENV[env] = k


# -- the declarations: one line per knob, THE inventory ---------------------
#
# mutability discipline: DYNAMIC is reserved for the knobs the tuner is
# allowed to move — pure performance trade-offs whose every setting is
# result-identical (coalesce window, step-chunk size, cache byte
# budgets, profiler stride).  Anything a bit-identity oracle pins
# (quantization modes, speculation depth, cache-composition toggles,
# topology) is STATIC by declaration.

# serve tier
_knob("serve.coalesce_us", "PATHWAY_SERVE_COALESCE_US", "float", 2000.0,
      "scheduler coalescing window in µs (0 = no wait)",
      lo=0.0, hi=100_000.0, mutability=DYNAMIC)
_knob("serve.max_batch", "PATHWAY_SERVE_MAX_BATCH", "int", 64,
      "cap on UNIQUE queries per coalesced device batch", lo=1, hi=4096)
_knob("serve.shards", "PATHWAY_SERVE_SHARDS", "int", 0,
      "serve-side index shard count (0 = caller/device default)",
      lo=0, hi=4096)
_knob("serve.deadline_ms", "PATHWAY_SERVE_DEADLINE_MS", "float", 0.0,
      "per-request serve deadline in ms (0 = none)", lo=0.0, hi=600_000.0)
_knob("serve.stage1_fraction", "PATHWAY_SERVE_STAGE1_FRACTION", "float", 0.6,
      "fraction of the deadline granted to stage 1", lo=0.05, hi=1.0)
_knob("serve.shed", "PATHWAY_SERVE_SHED", "bool", True,
      "SLO burn sheds shed-class requests at admission (off = advisory "
      "log-only, the pre-round-19 behavior)")
_knob("serve.shed_priorities", "PATHWAY_SERVE_SHED_PRIORITIES", "str", "low",
      "comma-separated priority classes eligible for load shedding")
_knob("serve.default_priority", "PATHWAY_SERVE_DEFAULT_PRIORITY", "enum",
      "normal", "priority class for submit() calls that pass none",
      choices=("high", "normal", "low"))

# serve fabric (serve/fabric.py) — the cross-process replica-group tier
_knob("fabric.heartbeat_s", "PATHWAY_FABRIC_HEARTBEAT", "float", 0.5,
      "fabric host heartbeat ping interval in seconds",
      lo=0.01, hi=3600.0)
_knob("fabric.heartbeat_timeout_s", "PATHWAY_FABRIC_HEARTBEAT_TIMEOUT",
      "float", 2.0, "heartbeat silence before a fabric host is declared "
      "dead (breaker trips, in-flight tickets re-route)",
      lo=0.05, hi=86_400.0)
_knob("fabric.hedge_ms", "PATHWAY_FABRIC_HEDGE_MS", "float", 0.0,
      "hedged-retry delay in ms: a request unanswered past this is "
      "re-sent to a second healthy host, first response wins (0 = off)",
      lo=0.0, hi=600_000.0, mutability=DYNAMIC)
_knob("fabric.affinity_slack", "PATHWAY_FABRIC_AFFINITY_SLACK", "int", 2,
      "extra in-flight requests the consistent-hash affinity host may "
      "carry over the least-loaded host before routing spills",
      lo=0, hi=4096)
_knob("fabric.connect_timeout_s", "PATHWAY_FABRIC_CONNECT_TIMEOUT",
      "float", 5.0, "fabric host TCP connect timeout in seconds",
      lo=0.05, hi=600.0)
_knob("fabric.request_timeout_s", "PATHWAY_FABRIC_REQUEST_TIMEOUT",
      "float", 30.0, "fallback per-request response timeout in seconds "
      "for requests that carry no deadline", lo=0.05, hi=86_400.0)

# partitioned serve fabric (serve/fabric.py scatter-gather)
_knob("fabric.partitions", "PATHWAY_FABRIC_PARTITIONS", "int", 0,
      "index partitions across the fabric fleet (0 = replica mode, "
      "every host holds the full index; N > 0 = each host owns "
      "doc_key % N of the corpus and serves scatter-gather)",
      lo=0, hi=4096)
_knob("partition.gather_timeout_s", "PATHWAY_PARTITION_GATHER_TIMEOUT",
      "float", 10.0, "scatter-gather straggler bound in seconds: a "
      "partition unanswered past it is flagged partition_lost and the "
      "surviving partitions' merge is served", lo=0.05, hi=86_400.0,
      mutability=DYNAMIC)
_knob("partition.absorb_timeout_s", "PATHWAY_PARTITION_ABSORB_TIMEOUT",
      "float", 30.0, "owner-routed absorb ack timeout in seconds before "
      "the routed batch is counted dropped on its owner partition",
      lo=0.05, hi=86_400.0)

# durable warm state (serve/warmstate.py)
_knob("warmstate.interval_s", "PATHWAY_WARMSTATE_INTERVAL_S", "float",
      60.0, "warm-state snapshot cadence in seconds (0 = manual only)",
      lo=0.0, hi=86_400.0, mutability=DYNAMIC)
_knob("warmstate.chunk_bytes", "PATHWAY_WARMSTATE_CHUNK_BYTES", "int",
      1_048_576, "CRC-framed snapshot chunk size in bytes",
      lo=4096, hi=1_073_741_824)
_knob("warmstate.keep", "PATHWAY_WARMSTATE_KEEP", "int", 2,
      "committed snapshot generations retained per store",
      lo=1, hi=1024)

# live ingest (serve/ingest.py)
_knob("ingest.batch_docs", "PATHWAY_INGEST_BATCH_DOCS", "int", 32,
      "max documents one ingest embed/absorb batch carries",
      lo=1, hi=4096, mutability=DYNAMIC)
_knob("ingest.poll_ms", "PATHWAY_INGEST_POLL_MS", "float", 5.0,
      "ingest loop idle poll interval in ms", lo=0.1, hi=60_000.0,
      mutability=DYNAMIC)
_knob("ingest.queue_cap", "PATHWAY_INGEST_QUEUE_CAP", "int", 4096,
      "pending-document queue capacity (connector commits block past it)",
      lo=1, hi=1_048_576)
_knob("ingest.backpressure_ms", "PATHWAY_INGEST_BACKPRESSURE_MS", "float",
      25.0, "absorb-cadence yield when serve latency is the binding SLO",
      lo=0.0, hi=60_000.0, mutability=DYNAMIC)

# continuous decode / generator
_knob("decode.step_bucket", "PATHWAY_DECODE_STEP_BUCKET", "int", 8,
      "decode steps one compiled chunk dispatch advances",
      lo=1, hi=128, mutability=DYNAMIC)
_knob("decode.slots", "PATHWAY_DECODE_SLOTS", "int", 8,
      "continuous-decode slot-pool size", lo=1, hi=1024)
_knob("decode.kv_width", "PATHWAY_DECODE_KV_WIDTH", "int", 0,
      "slot-pool context width override (0 = model max_len)",
      lo=0, hi=1_048_576)
_knob("decode.kv_quant", "PATHWAY_DECODE_KV_QUANT", "enum", "bf16",
      "slot-pool K/V storage (bit-identity oracle pins this)",
      choices=("bf16", "int8"))
_knob("decode.spec_k", "PATHWAY_DECODE_SPEC_K", "int", 0,
      "speculation depth per verify dispatch (0 = off; token-identity "
      "oracle pins this)", lo=0, hi=16)
_knob("decode.draft", "PATHWAY_DECODE_DRAFT", "enum", "auto",
      "speculative draft source", choices=("auto", "ngram", "trunk"))
_knob("decode.draft_layers", "PATHWAY_DECODE_DRAFT_LAYERS", "int", 0,
      "reduced-layer draft-trunk depth (0 = half the trunk)",
      lo=0, hi=1024)
_knob("generator.eos", "PATHWAY_GENERATOR_EOS", "str", "",
      "EOS token id for early stop (empty/none = no EOS handling)")
_knob("generator.kv", "PATHWAY_GENERATOR_KV", "bool", True,
      "generator-side prefix K/V reuse")
_knob("chat.continuous", "PATHWAY_CHAT_CONTINUOUS", "bool", False,
      "route xpack chat through the continuous decoder")
_knob("qa.rerank_coalesce", "PATHWAY_QA_RERANK_COALESCE", "bool", False,
      "coalesce concurrent QA rerank dispatches via SharedBatcher")

# cache tiers
_knob("cache.enabled", "PATHWAY_CACHE", "bool", True,
      "global cache kill switch (off disables every tier)")
_knob("cache.result", "PATHWAY_CACHE_RESULT", "bool", True,
      "tier-0 result cache")
_knob("cache.result_bytes", "PATHWAY_CACHE_RESULT_BYTES", "int", 32 << 20,
      "result-tier byte budget", lo=0, hi=1 << 40, mutability=DYNAMIC)
_knob("cache.result_ttl_s", "PATHWAY_CACHE_RESULT_TTL_S", "float", 60.0,
      "result-tier TTL in seconds (0 = no expiry)", lo=0.0, hi=86_400.0)
_knob("cache.embed", "PATHWAY_CACHE_EMBED", "bool", False,
      "tier-1 embedding cache (opt-in: swaps the fused kernel for the "
      "split pair, changing low-order score bits)")
_knob("cache.embed_bytes", "PATHWAY_CACHE_EMBED_BYTES", "int", 64 << 20,
      "embedding-tier byte budget", lo=0, hi=1 << 40, mutability=DYNAMIC)
_knob("cache.embed_ttl_s", "PATHWAY_CACHE_EMBED_TTL_S", "float", 0.0,
      "embedding-tier TTL in seconds (0 = no expiry)", lo=0.0, hi=86_400.0)
_knob("cache.kv", "PATHWAY_CACHE_KV", "bool", True,
      "tier-2 generator prefix-KV cache")
_knob("cache.kv_bytes", "PATHWAY_CACHE_KV_BYTES", "int", 256 << 20,
      "prefix-KV-tier byte budget", lo=0, hi=1 << 40, mutability=DYNAMIC)
_knob("cache.kv_ttl_s", "PATHWAY_CACHE_KV_TTL_S", "float", 0.0,
      "prefix-KV-tier TTL in seconds (0 = no expiry)", lo=0.0, hi=86_400.0)
_knob("cache.kv_block", "PATHWAY_CACHE_KV_BLOCK", "int", 32,
      "prefix-KV block size in tokens (key-chain granularity)",
      lo=1, hi=4096)

# index
_knob("forward.tokens", "PATHWAY_FORWARD_TOKENS", "int", 16,
      "forward-index pooled doc-row budget T'", lo=1, hi=4096)
_knob("forward.quant", "PATHWAY_FORWARD_QUANT", "enum", "int8",
      "forward-index row storage (parity oracle pins this)",
      choices=("int8", "none"))

# observability
_knob("observe.enabled", "PATHWAY_OBSERVE", "bool", True,
      "flight recorder + tracing + profiling master switch")
_knob("observe.trace_sample", "PATHWAY_TRACE_SAMPLE", "float", 1.0,
      "head-sampling probability for request traces", lo=0.0, hi=1.0)
_knob("observe.trace_keep", "PATHWAY_TRACE_KEEP", "int", 256,
      "kept-trace LRU capacity on GET /traces", lo=1, hi=65_536)
_knob("observe.trace_pending", "PATHWAY_TRACE_PENDING", "int", 128,
      "pending-trace ring capacity", lo=1, hi=65_536)
_knob("observe.trace_max_spans", "PATHWAY_TRACE_MAX_SPANS", "int", 192,
      "span cap per trace tree", lo=8, hi=65_536)
_knob("observe.trace_slow_pct", "PATHWAY_TRACE_SLOW_PCT", "float", 0.99,
      "tail-sampling slow-percentile threshold", lo=0.5, hi=0.9999)
_knob("observe.profile_sample", "PATHWAY_PROFILE_SAMPLE", "float", 0.25,
      "device-time profiler sampled fraction of calls",
      lo=0.0, hi=1.0, mutability=DYNAMIC)
_knob("observe.slo", "PATHWAY_SLO", "bool", True,
      "SLO engine shed-advisory probe in scheduler admission")
_knob("observe.slo_tick_s", "PATHWAY_SLO_TICK_S", "float", 1.0,
      "min seconds between SLO burn-rate evaluations", lo=0.0, hi=3600.0)
_knob("observe.slo_latency_ms", "PATHWAY_SLO_LATENCY_MS", "float", 500.0,
      "serve-latency SLO threshold in ms", lo=1.0, hi=600_000.0)
_knob("observe.slo_latency_objective", "PATHWAY_SLO_LATENCY_OBJECTIVE",
      "float", 0.99, "serve-latency SLO objective fraction",
      lo=0.5, hi=0.99999)
_knob("observe.slo_availability", "PATHWAY_SLO_AVAILABILITY", "float", 0.999,
      "availability SLO objective fraction", lo=0.5, hi=0.99999)
_knob("observe.slo_ttlt_ms", "PATHWAY_SLO_TTLT_MS", "float", 2000.0,
      "decode TTLT SLO threshold in ms", lo=1.0, hi=600_000.0)
_knob("observe.slo_freshness_ms", "PATHWAY_SLO_FRESHNESS_MS", "float",
      5000.0, "ingest freshness SLO threshold in ms (arrival to "
      "retrievable)", lo=1.0, hi=86_400_000.0)
_knob("observe.slo_freshness_objective", "PATHWAY_SLO_FRESHNESS_OBJECTIVE",
      "float", 0.99, "freshness SLO objective fraction", lo=0.5, hi=0.99999)
_knob("observe.slo_fast_window_s", "PATHWAY_SLO_FAST_WINDOW_S", "float",
      300.0, "fast burn-rate window in seconds", lo=0.05, hi=86_400.0)
_knob("observe.slo_slow_window_s", "PATHWAY_SLO_SLOW_WINDOW_S", "float",
      3600.0, "slow burn-rate window in seconds", lo=0.05, hi=86_400.0)
_knob("observe.slo_burn", "PATHWAY_SLO_BURN", "float", 14.4,
      "burn-rate multiple that fires the SLO alert", lo=0.1, hi=10_000.0)
_knob("observe.monitoring_server", "PATHWAY_MONITORING_SERVER", "str", "",
      "OTLP endpoint for span export (empty = off)")
_knob("observe.metrics_port", "PATHWAY_METRICS_PORT", "int", 20000,
      "/metrics HTTP port", lo=1, hi=65_535)
_knob("observe.metrics_host", "PATHWAY_METRICS_HOST", "str", "127.0.0.1",
      "/metrics bind host")

# self-tuning (serve/tuner.py)
_knob("tuner.enabled", "PATHWAY_TUNER", "bool", False,
      "background knob tuner (adjusts dynamic-class knobs online)")
_knob("tuner.interval_s", "PATHWAY_TUNER_INTERVAL_S", "float", 2.0,
      "seconds between tuner control ticks", lo=0.05, hi=3600.0)

# robustness
_knob("robust.faults", "PATHWAY_FAULTS", "str", "",
      "armed chaos sites, e.g. 'cache.get=error:p=0.01'")
_knob("robust.retry_attempts", "PATHWAY_RETRY_ATTEMPTS", "int", 3,
      "retry attempts per site", lo=1, hi=100,
      site_prefix="PATHWAY_RETRY_ATTEMPTS_")
_knob("robust.retry_base_ms", "PATHWAY_RETRY_BASE_MS", "float", 5.0,
      "retry backoff base delay in ms", lo=0.0, hi=60_000.0)
_knob("robust.retry_max_ms", "PATHWAY_RETRY_MAX_MS", "float", 200.0,
      "retry backoff max delay in ms", lo=0.0, hi=600_000.0)
_knob("robust.retry_seed", "PATHWAY_RETRY_SEED", "int", 0,
      "retry jitter seed (replayable soaks)", lo=0, hi=2**31 - 1)
_knob("robust.breaker_threshold", "PATHWAY_BREAKER_THRESHOLD", "int", 5,
      "consecutive failures that open a circuit breaker", lo=1, hi=10_000)
_knob("robust.breaker_reset_s", "PATHWAY_BREAKER_RESET_S", "float", 30.0,
      "open-breaker half-open probe delay in seconds", lo=0.0, hi=86_400.0)

# runtime tripwires
_knob("ops.donation_guard", "PATHWAY_DONATION_GUARD", "bool", False,
      "runtime use-after-donate tripwire")
_knob("ops.donation_guard_strict", "PATHWAY_DONATION_GUARD_STRICT", "bool",
      False, "donation tripwire raises instead of degrade-and-count",
      auto_pytest=True)
_knob("ops.recompile_limit", "PATHWAY_RECOMPILE_LIMIT", "int", 128,
      "compiled-signature budget per jitted callable", lo=1, hi=1_000_000)
_knob("ops.recompile_strict", "PATHWAY_RECOMPILE_STRICT", "bool", False,
      "recompile tripwire raises instead of warn-once", auto_pytest=True)
_knob("analysis.cache_dir", "PATHWAY_ANALYSIS_CACHE", "str", "",
      "incremental analyzer cache directory (empty = cold runs)")
_knob("analysis.lock_sanitizer", "PATHWAY_LOCK_SANITIZER", "bool", False,
      "runtime lock-order sanitizer (proxies pathway locks)")
_knob("analysis.lock_sanitizer_raise", "PATHWAY_LOCK_SANITIZER_RAISE",
      "bool", False, "sanitizer raises on a would-be inversion",
      auto_pytest=True)
_knob("analysis.lock_hold_ms", "PATHWAY_LOCK_HOLD_MS", "float", 0.0,
      "sanitizer lock-hold budget in ms (0 = off)", lo=0.0, hi=60_000.0)

# topology / parallel planes
_knob("parallel.processes", "PATHWAY_PROCESSES", "int", 1,
      "process-cluster size", lo=1, hi=65_536)
_knob("parallel.process_id", "PATHWAY_PROCESS_ID", "int", 0,
      "this process's cluster rank", lo=0, hi=65_535)
_knob("parallel.coordinator_address", "PATHWAY_COORDINATOR_ADDRESS", "str",
      "", "jax distributed coordinator host:port")
_knob("parallel.first_port", "PATHWAY_FIRST_PORT", "str", "",
      "first port of the spawned cluster's port range")
_knob("parallel.exchange_host", "PATHWAY_EXCHANGE_HOST", "str", "",
      "advertised host for the TCP exchange plane")
_knob("parallel.exchange_heartbeat_s", "PATHWAY_EXCHANGE_HEARTBEAT",
      "float", 2.0, "exchange-plane heartbeat interval in seconds",
      lo=0.05, hi=3600.0)
_knob("parallel.exchange_heartbeat_timeout_s",
      "PATHWAY_EXCHANGE_HEARTBEAT_TIMEOUT", "float", 8.0,
      "peer-lost declaration timeout in seconds", lo=0.1, hi=86_400.0)
_knob("parallel.data_shards", "PATHWAY_TPU_DATA_SHARDS", "int", 0,
      "mesh data-axis size override (0 = derive)", lo=0, hi=65_536)
_knob("parallel.model_shards", "PATHWAY_TPU_MODEL_SHARDS", "int", 0,
      "mesh model-axis size override (0 = derive)", lo=0, hi=65_536)
_knob("native.disable", "PATHWAY_TPU_DISABLE_NATIVE", "bool", False,
      "skip building/loading the native library")
_knob("cli.spawn_args", "PATHWAY_SPAWN_ARGS", "str", "",
      "extra args for `pathway spawn-from-env`")

# engine / persistence
_knob("engine.commit_duration_ms", "PATHWAY_COMMIT_DURATION_MS", "int", 100,
      "dataflow commit-tick duration in ms", lo=1, hi=3_600_000)
_knob("engine.terminate_on_error", "PATHWAY_TERMINATE_ON_ERROR", "bool",
      True, "tear the graph down on an operator error")
_knob("engine.runtime_typechecking", "PATHWAY_RUNTIME_TYPECHECKING", "bool",
      False, "per-row schema checks in the engine")
_knob("persistence.mode", "PATHWAY_PERSISTENCE_MODE", "str", "",
      "persistence mode (empty = off)")
_knob("persistence.replay_storage", "PATHWAY_REPLAY_STORAGE", "str", "",
      "replay storage URI (empty = off)")
_knob("persistence.storage", "PATHWAY_PERSISTENT_STORAGE", "str", "",
      "snapshot storage URI (empty = off)")
_knob("persistence.snapshot_interval_ms", "PATHWAY_SNAPSHOT_INTERVAL_MS",
      "int", 60_000, "snapshot cadence in ms", lo=1, hi=86_400_000)
_knob("license.key", "PATHWAY_LICENSE_KEY", "str", "",
      "accepted and ignored (this framework is fully open)")


# -- parse + clamp ----------------------------------------------------------

def _warn_once(tag: str, msg: str, *args: Any) -> None:
    if tag in _warned:
        return
    _warned.add(tag)
    _log.warning(msg, *args)


def _clamp_num(knob: Knob, value: float) -> float:
    out = value
    if knob.lo is not None and out < knob.lo:
        out = knob.lo
    if knob.hi is not None and out > knob.hi:
        out = knob.hi
    if out != value:
        _warn_once(
            f"clamp:{knob.env}:{value}",
            "%s=%r outside declared bounds [%s, %s]; clamped to %r",
            knob.env, value, knob.lo, knob.hi, out,
        )
    return out


def _parse(knob: Knob, raw: Optional[str]) -> Any:
    """Raw env string -> typed, clamped value.  NEVER raises: garbage
    degrades to the declared default with one log line — a poisoned env
    must cost a warning, not a failed serve."""
    if raw is None:
        if knob.auto_pytest:
            return "PYTEST_CURRENT_TEST" in os.environ
        default = knob.default
    else:
        s = raw.strip()
        if knob.kind == "bool":
            low = s.lower()
            if low in _TRUE:
                return True
            if low in _FALSE:
                return False
            _warn_once(
                f"bool:{knob.env}:{s}",
                "%s=%r is not a recognized bool (%s/%s); using default %r",
                knob.env, raw, "|".join(_TRUE), "|".join(_FALSE),
                knob.default,
            )
            default = knob.default
        elif knob.kind in ("int", "float"):
            try:
                num = int(s) if knob.kind == "int" else float(s)
            except ValueError:
                _warn_once(
                    f"num:{knob.env}:{s}",
                    "%s=%r does not parse as %s; using default %r",
                    knob.env, raw, knob.kind, knob.default,
                )
                default = knob.default
            else:
                out = _clamp_num(knob, num)
                return int(out) if knob.kind == "int" else float(out)
        elif knob.kind == "enum":
            low = s.lower()
            if low in (knob.choices or ()):
                return low
            _warn_once(
                f"enum:{knob.env}:{s}",
                "%s=%r not in %s; using default %r",
                knob.env, raw, knob.choices, knob.default,
            )
            default = knob.default
        else:  # str
            return raw
    if knob.auto_pytest and default is None:
        return "PYTEST_CURRENT_TEST" in os.environ
    if knob.kind in ("int", "float") and default is not None:
        out = _clamp_num(knob, default)
        return int(out) if knob.kind == "int" else float(out)
    return default


def _spec(key: str) -> Knob:
    knob = _REGISTRY.get(key)
    if knob is None:
        raise UnknownKnobError(key)
    return knob


# -- the read path ----------------------------------------------------------

def get(key: str, fallback: Any = None) -> Any:
    """The typed value of one declared knob: tuner override (dynamic
    knobs only) > env > ``fallback`` (a SITE default for knobs like
    ``serve.shards`` whose neutral registry default means "ask the
    caller") > declared default.  The parse is cached keyed on the raw
    env string, so steady-state cost is three dict probes and a string
    compare — no per-request ``int()``/``float()``."""
    ov = _overrides.get(key)
    if ov is not None:
        return ov
    knob = _spec(key)
    raw = os.environ.get(knob.env)
    if knob.auto_pytest:
        return _parse(knob, raw)  # volatile: pytest marker moves per test
    if raw is None and fallback is not None:
        if knob.kind in ("int", "float"):
            out = _clamp_num(knob, fallback)
            return int(out) if knob.kind == "int" else float(out)
        return fallback
    cached = _cache.get(key)
    if cached is not None and cached[0] == raw:
        return cached[1]
    value = _parse(knob, raw)
    _cache[key] = (raw, value)
    return value


def get_site(key: str, site: str) -> Any:
    """Per-site override family: ``get_site("robust.retry_attempts",
    "cache.get")`` reads ``PATHWAY_RETRY_ATTEMPTS_CACHE_GET`` (site
    upper-cased, ``.``/``-`` -> ``_``) parsed+clamped under the SAME
    declaration, falling back to the base knob."""
    knob = _spec(key)
    if not knob.site_prefix:
        return get(key)
    env_name = knob.site_prefix + site.upper().replace(".", "_").replace(
        "-", "_"
    )
    raw = os.environ.get(env_name)
    if raw is None:
        return get(key)
    ck = f"{key}@{env_name}"
    cached = _cache.get(ck)
    if cached is not None and cached[0] == raw:
        return cached[1]
    value = _parse(knob, raw)
    _cache[ck] = (raw, value)
    return value


# -- the tuner write path ---------------------------------------------------

def set(key: str, value: Any) -> Any:  # noqa: A001 - the module IS the namespace
    """Adjust a ``dynamic`` knob online (the tuner's only write path).
    The value is clamped to the declared bounds and layered OVER the
    env; returns the applied value.  ``static`` knobs raise
    ``StaticKnobError`` — the declaration is the veto, so a knob a
    bit-identity oracle pins cannot move no matter what a controller
    computes."""
    knob = _spec(key)
    if knob.mutability != DYNAMIC:
        raise StaticKnobError(
            f"knob {key} ({knob.env}) is static by declaration; "
            "the tuner may only adjust dynamic-class knobs"
        )
    if knob.kind == "int":
        applied: Any = int(_clamp_num(knob, int(value)))
    elif knob.kind == "float":
        applied = float(_clamp_num(knob, float(value)))
    else:
        applied = _parse(knob, str(value))
    with _lock:
        _overrides[key] = applied
    return applied


def clear_override(key: str) -> None:
    """Drop one tuner override: the knob reverts to env/default."""
    with _lock:
        _overrides.pop(key, None)


def clear_overrides() -> None:
    with _lock:
        _overrides.clear()


def overrides() -> Dict[str, Any]:
    """Snapshot of the live tuner layer (key -> applied value)."""
    return dict(_overrides)


# -- load / introspection ---------------------------------------------------

def load() -> Dict[str, Any]:
    """Parse EVERY declared knob from the current env into the cache and
    return the snapshot.  Chaos-instrumented (``config.load``): a fault
    here degrades to the last-good cached values — a poisoned reload is
    a warning and a counter, never a failed serve."""
    try:
        from .robust import inject

        inject.fire("config.load")
    except ImportError:
        pass
    except Exception as exc:
        _warn_once(
            f"load:{type(exc).__name__}",
            "config.load failed (%r); serving last-good knob values", exc,
        )
        try:
            from . import observe

            observe.counter("pathway_config_load_failures_total").inc()
        except Exception:
            pass
        return snapshot()
    for key in _REGISTRY:
        knob = _REGISTRY[key]
        if knob.auto_pytest:
            continue
        raw = os.environ.get(knob.env)
        _cache[key] = (raw, _parse(knob, raw))
    return snapshot()


def snapshot() -> Dict[str, Any]:
    """{key: effective typed value} for every declared knob."""
    return {key: get(key) for key in sorted(_REGISTRY)}


def registry() -> Dict[str, Knob]:
    """The declarations, read-only by convention."""
    return dict(_REGISTRY)


def knobs() -> List[Knob]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def describe() -> List[Dict[str, Any]]:
    """One JSON-able row per knob — the CLI/README table source."""
    rows = []
    for knob in knobs():
        bounds = ""
        if knob.lo is not None or knob.hi is not None:
            bounds = f"[{knob.lo!r}, {knob.hi!r}]"
        elif knob.choices:
            bounds = "|".join(knob.choices)
        rows.append(
            {
                "key": knob.key,
                "env": knob.env
                + ("(_<SITE>)" if knob.site_prefix else ""),
                "type": knob.kind,
                "default": knob.default_doc(),
                "bounds": bounds,
                "mutability": knob.mutability,
                "doc": knob.doc,
            }
        )
    return rows


_COLUMNS = ("key", "env", "type", "default", "bounds", "mutability", "doc")


def markdown_table() -> str:
    """The README "Configuration" table — generated here so the README
    drift test can gate doc ⊆ registry and registry ⊆ doc byte-for-byte
    on the env-name column."""
    rows = describe()
    lines = [
        "| key | env | type | default | bounds | mutability | doc |",
        "| --- | --- | --- | --- | --- | --- | --- |",
    ]
    for r in rows:
        lines.append(
            "| `{key}` | `{env}` | {type} | {default} | {bounds} | "
            "{mutability} | {doc} |".format(**r)
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.config",
        description="The declarative PATHWAY_* knob registry.",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "markdown"), default="text",
        dest="fmt", help="table output format",
    )
    args = parser.parse_args(argv)
    if args.fmt == "json":
        print(json.dumps(describe(), indent=1, sort_keys=True))
    elif args.fmt == "markdown":
        print(markdown_table())
    else:
        rows = describe()
        widths = {
            c: max(len(c), *(len(str(r[c])) for r in rows))
            for c in _COLUMNS[:-1]
        }
        print("  ".join(c.ljust(widths[c]) for c in _COLUMNS[:-1]) + "  doc")
        for r in rows:
            print(
                "  ".join(
                    str(r[c]).ljust(widths[c]) for c in _COLUMNS[:-1]
                )
                + "  "
                + r["doc"]
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
