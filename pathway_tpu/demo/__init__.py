"""Synthetic demo streams
(reference: python/pathway/demo/__init__.py:28-258 — range_stream,
noisy_linear_stream, generate_custom_stream, replay_csv[_with_time])."""

from __future__ import annotations

import csv as _csv
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional, Type

from ..internals import dtype as dt
from ..internals.schema import Schema, schema_from_types
from ..internals.table import Table

__all__ = [
    "generate_custom_stream",
    "range_stream",
    "noisy_linear_stream",
    "replay_csv",
    "replay_csv_with_time",
]


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema: Type[Schema],
    nb_rows: Optional[int] = None,
    input_rate: float = 1.0,
    autocommit_duration_ms: int = 100,
    persistent_id: Optional[str] = None,
) -> Table:
    """Stream rows produced by per-column generators at ``input_rate`` rows/s
    (reference: demo/__init__.py:28)."""
    from ..io.python import ConnectorSubject, read

    class _GenSubject(ConnectorSubject):
        def run(self):
            i = 0
            while nb_rows is None or i < nb_rows:
                row = {name: gen(i) for name, gen in value_generators.items()}
                self.next(**row)
                i += 1
                if input_rate > 0:
                    time.sleep(1.0 / input_rate)

    return read(_GenSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms)


def range_stream(
    nb_rows: Optional[int] = None,
    offset: int = 0,
    input_rate: float = 1.0,
    **kwargs,
) -> Table:
    return generate_custom_stream(
        {"value": lambda i: i + offset},
        schema=schema_from_types(value=int),
        nb_rows=nb_rows,
        input_rate=input_rate,
        **kwargs,
    )


def noisy_linear_stream(nb_rows: int = 10, input_rate: float = 1.0, **kwargs) -> Table:
    import random

    return generate_custom_stream(
        {"x": lambda i: float(i), "y": lambda i: float(i) + random.uniform(-1, 1)},
        schema=schema_from_types(x=float, y=float),
        nb_rows=nb_rows,
        input_rate=input_rate,
        **kwargs,
    )


def replay_csv(
    path: str,
    *,
    schema: Type[Schema],
    input_rate: float = 1.0,
) -> Table:
    """Replay a CSV file as a stream (reference: demo/__init__.py:212)."""
    from ..io.python import ConnectorSubject, read

    columns = list(schema.columns().keys())
    dtypes = schema.typehints()

    class _ReplaySubject(ConnectorSubject):
        def run(self):
            with open(path, newline="") as f:
                for row in _csv.DictReader(f):
                    out = {}
                    for c in columns:
                        v = row.get(c)
                        t = dt.unoptionalize(dtypes.get(c, dt.ANY))
                        if v is not None:
                            if t is dt.INT:
                                v = int(v)
                            elif t is dt.FLOAT:
                                v = float(v)
                            elif t is dt.BOOL:
                                v = v.lower() in ("1", "true", "yes")

                        out[c] = v
                    self.next(**out)
                    if input_rate > 0:
                        time.sleep(1.0 / input_rate)

    return read(_ReplaySubject(), schema=schema)


def replay_csv_with_time(
    path: str,
    *,
    schema: Type[Schema],
    time_column: str,
    unit: str = "s",
    autocommit_ms: int = 100,
    speedup: float = 1,
) -> Table:
    """Replay respecting inter-row gaps in ``time_column``
    (reference: demo/__init__.py:258)."""
    from ..io.python import ConnectorSubject, read

    columns = list(schema.columns().keys())
    dtypes = schema.typehints()
    mul = {"s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}[unit]

    class _ReplayTimeSubject(ConnectorSubject):
        def run(self):
            prev_t = None
            with open(path, newline="") as f:
                for row in _csv.DictReader(f):
                    out = {}
                    for c in columns:
                        v = row.get(c)
                        t = dt.unoptionalize(dtypes.get(c, dt.ANY))
                        if v is not None:
                            if t is dt.INT:
                                v = int(v)
                            elif t is dt.FLOAT:
                                v = float(v)

                        out[c] = v
                    t_now = float(out[time_column]) * mul
                    if prev_t is not None and t_now > prev_t:
                        time.sleep((t_now - prev_t) / speedup)
                    prev_t = t_now
                    self.next(**out)

    return read(_ReplayTimeSubject(), schema=schema, autocommit_duration_ms=autocommit_ms)
