"""Serve-path front-end: continuous cross-request batching.

The fused retrieve→rerank pipeline (ops/retrieve_rerank.py) meets its
latency budget per CALL — 2 dispatches + 2 fetches — but concurrent
callers each pay that budget alone and serialize behind one another.
``ServeScheduler`` coalesces concurrent serve calls into shared bucketed
device batches (one 2+2 budget amortized across every rider) and
double-buffers them so stage-2 rerank of batch N overlaps stage-1
encode/search of batch N+1; ``SharedBatcher`` is the same engine for
flat scoring calls (e.g. the QA layer's cross-encoder rerank).

``ContinuousDecoder`` (decode.py) extends the same admission machinery
to GENERATOR decode at token granularity: a persistent slotted K/V pool
where requests join after a (prefix-cached) prefill and leave at EOS,
freeing their slot mid-flight — the throughput substrate for the
cascade's listwise LLM rerank stage and the chat/QA path.

``LiveIngestRunner`` (ingest.py) closes the loop with the incremental
half of the reference: committed connector rows are embedded in
off-serve-path batches and absorbed into the live indexes under serve
traffic, with the freshness plane (``pathway_freshness_seconds``,
ingest traces, maintenance-lag gauges, the freshness SLO) attributing
every ingest→retrievable journey.
"""

from .decode import ContinuousDecoder, DecodeResult, decode_slots
from .fabric import FabricWorker, ServeFabric, fabric_token
from .ingest import IngestConnector, LiveIngestRunner, ingest_runners
from .scheduler import ServeScheduler, SharedBatcher, coalesce_window_s, max_batch_queries
from .tuner import Tuner, tuner_from_env
from .warmstate import RestoreReport, WarmStateManager

__all__ = [
    "ContinuousDecoder",
    "DecodeResult",
    "FabricWorker",
    "IngestConnector",
    "LiveIngestRunner",
    "RestoreReport",
    "ServeFabric",
    "ServeScheduler",
    "SharedBatcher",
    "Tuner",
    "WarmStateManager",
    "coalesce_window_s",
    "decode_slots",
    "fabric_token",
    "ingest_runners",
    "max_batch_queries",
    "tuner_from_env",
]
