"""Continuous cross-request batching: a coalescing serve scheduler with
double-buffered stage pipelining.

The serve path is RTT-bound and the fused pipeline already hits the
2-dispatch + 2-fetch budget — but only *per request*: concurrent callers
serialize on the pipeline, so at QPS above 1/RTT the device idles while
requests queue.  Cross-request micro-batching is the standard fix in
neural-ranking serving ("Accelerating Retrieval-Augmented Generation",
arxiv 2412.15246; Zamani et al., arxiv 1707.08275: retrieval+rerank
throughput is dominated by batch occupancy, not per-query FLOPs).

One scheduler thread owns admission; the **future-handoff** contract
splits the work so no thread ever blocks while holding the queue lock:

    caller ──submit()──► admission queue ──window──► scheduler thread
                                                  │  sorted-unique pack,
                                                  │  ONE stage-1 dispatch
                                                  │  (batch N), then
                                                  │  advance(batch N-1)
    caller ◄──ticket()─── per-request demux ◄─────┘
              (the WAITER performs the host fetch)

- **Coalescing window**: ``PATHWAY_SERVE_COALESCE_US`` (default 2000)
  anchored at the oldest queued request, capped by every queued
  request's ``Deadline`` slack — the window never eats more than half
  of any rider's remaining budget, and a request admitted with almost
  no slack serves SOLO on its own thread instead of waiting at all.
- **Double-buffered pipelining**: after dispatching batch N's stage 1
  the scheduler ``advance()``s batch N-1 (completing its stage-1 fetch
  and dispatching its stage-2 rerank), so stage 2 of N-1 overlaps
  stage 1 of N on the device — the 2+2 dispatch budget is paid once
  *per batch* and amortized across every coalesced request.
- **Dedup**: hash-identical texts inside a window encode once; the
  packed results scatter to every waiter.  Batch composition is the
  *sorted* unique text list, so identical windows produce bit-identical
  device batches (and therefore bit-identical results) regardless of
  thread arrival order.
- **Tier-0 result cache** (``pathway_tpu/cache``): cross-WINDOW repeats
  — the hot-head traffic in-window dedup cannot see — resolve before
  admission under ``(text, index generation, k)``: zero dispatches, no
  window wait, generation-bump invalidation (see ``ServeScheduler``).
- **Degradation stays per-request**: a stage-1 failure inside a
  coalesced batch flags ``retrieval_failed`` on (and counts) each rider
  of that batch, and the next batch starts clean — one bad window never
  poisons the scheduler.

The scheduler fronts anything with the repo's submit/complete serving
contract — ``submit(texts, k, deadline=...) -> handle`` where the handle
is a zero-arg completion, optionally with a non-blocking-ish
``advance()`` (``RetrieveRerankPipeline``, ``FusedEncodeSearch``).
``SharedBatcher`` reuses the same engine for flat scoring calls
(``submit(items, deadline=...) -> completion -> scores aligned with
items``, e.g. ``CrossEncoderModel.submit``) so the QA layer's rerank
stage coalesces across dataflow rows too.

Nothing in this module touches jax; the admission lock is held only for
list/int work (lock-discipline clean by construction, and the analyzer's
future-handoff rule keeps it that way).
"""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import contextlib
import inspect
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import config, observe
from ..cache import normalize_generation, query_key, result_cache_from_env
from ..observe import slo as slo_mod
from ..observe import trace
from ..robust import (
    Deadline,
    LOAD_SHED,
    RETRIEVAL_FAILED,
    ServeResult,
    log_once,
    record_degraded,
)

__all__ = [
    "ServeScheduler",
    "SharedBatcher",
    "coalesce_window_s",
    "max_batch_queries",
]


def coalesce_window_s() -> float:
    """Coalescing window from ``serve.coalesce_us`` (default 2000 µs,
    tuner-adjustable); 0 disables waiting (batches still form from
    whatever is queued when the scheduler thread comes around)."""
    return config.get("serve.coalesce_us") * 1e-6


def max_batch_queries() -> int:
    """Per-batch cap on UNIQUE queries from ``serve.max_batch``
    (default 64 — the second-largest stage-1 batch bucket, so one
    coalesced dispatch never jumps to a cold compile shape under a
    traffic spike).  The cap bounds the DEVICE batch, not admissions:
    duplicate queries ride a batch for free, so hot traffic packs many
    more requests than ``max_batch`` into one bucket-aligned dispatch."""
    return config.get("serve.max_batch")


# time-in-queue: enqueue → handoff of the shared batch to the waiters
# (shared series across scheduler instances, like the serve stage
# histograms; per-instance split rides the provider counters below)
_H_QUEUE_WAIT = observe.histogram("pathway_serve_queue_wait_seconds")

# requests shed at admission, by priority class — pre-created for the
# known classes so the family renders at 0 before the first shed
_C_SHED = {
    p: observe.counter("pathway_serve_shed_total", priority=p)
    for p in ("high", "normal", "low")
}


def _shed_classes() -> frozenset:
    """Priority classes eligible for shedding (``serve.shed_priorities``,
    CSV, default "low")."""
    raw = str(config.get("serve.shed_priorities"))
    return frozenset(p.strip().lower() for p in raw.split(",") if p.strip())

# stateless shared no-op context manager for the untraced fast path
_NOOP_CM = contextlib.nullcontext()


class _Request:
    """One admitted serve/score call: resolved by the scheduler with the
    shared batch + this request's slot mapping into it."""

    __slots__ = (
        "items", "k", "deadline", "t_enqueue_ns", "event", "batch", "slots",
        "cache_store", "trace",
    )

    def __init__(self, items: Sequence[Any], k: Optional[int], deadline):
        self.items = list(items)
        self.k = k
        self.deadline = deadline
        self.t_enqueue_ns = time.perf_counter_ns()
        self.event = threading.Event()
        self.batch: Optional["_Batch"] = None
        self.slots: List[int] = []
        # tier-0 capture flag: set at admission when a result cache is
        # armed (cache-hit tickets never re-store their own rows)
        self.cache_store = False
        # per-request TraceContext (observe/trace.py), created at
        # submit() admission and finished at demux — None when tracing
        # is off or the request was head-sampled out
        self.trace = None


class _Batch:
    """The future-handoff point: the scheduler thread created the handle
    (dispatch); whichever WAITER arrives first performs the host fetch.
    ``result()`` is idempotent and thread-safe — the per-batch lock only
    ever guards the once-only completion, never a queue."""

    __slots__ = ("_handle", "_n_items", "_n_requests", "_degrade_empty",
                 "_lock", "_done", "_result", "_error", "_trace")

    def __init__(self, handle, n_items: int, n_requests: int,
                 degrade_empty: bool, trace_ctx=None):
        self._handle = handle
        self._n_items = n_items
        self._n_requests = n_requests
        self._degrade_empty = degrade_empty
        self._lock = threading.Lock()
        self._done = False
        self._result: Any = None
        self._error: Optional[BaseException] = None
        # the BATCH trace (observe/trace.py): the shared work — stage-1
        # dispatch, shard fan-out, cascade stages — records into it;
        # advance()/result() re-activate it because they run on other
        # threads (scheduler thread / whichever waiter fetches first)
        self._trace = trace_ctx

    def advance(self) -> None:
        """Pipelining hook: complete stage 1 and dispatch stage 2 of this
        batch without blocking on the final fetch (no-op for handles
        without ``advance``).  Failures are deferred to ``result()`` —
        the ladder lands in one place."""
        adv = getattr(self._handle, "advance", None)
        if adv is None:
            return
        try:
            if self._trace is not None:
                with trace.use(self._trace):
                    adv()
            else:
                adv()
        except Exception:
            pass  # surfaces (once) at result() via the same handle

    def result(self) -> Any:
        with self._lock:
            if not self._done:
                try:
                    if self._trace is not None:
                        with trace.use(self._trace):
                            self._result = self._handle()
                    else:
                        self._result = self._handle()
                except Exception as exc:
                    if self._degrade_empty:
                        # a target without an internal degradation ladder
                        # (e.g. bare FusedEncodeSearch) raised past its
                        # retry budget: every rider of THIS batch is
                        # affected — flag and count each, serve empty
                        log_once(
                            f"scheduler.batch:{type(exc).__name__}",
                            "coalesced serve batch failed (%r); serving "
                            "empty degraded results to its riders",
                            exc,
                        )
                        record_degraded(RETRIEVAL_FAILED, self._n_requests)
                        self._result = ServeResult(
                            [[] for _ in range(self._n_items)],
                            degraded=(RETRIEVAL_FAILED,),
                        )
                    else:
                        self._error = exc
                self._done = True
                if self._trace is not None:
                    # finish INSIDE the batch lock: a rider's demux (and
                    # its link promotion) must never observe the batch
                    # trace unfinished once result() has returned
                    flags = tuple(getattr(self._result, "degraded", ()) or ())
                    if self._error is not None:
                        flags = flags + ("error",)
                    trace.finish(self._trace, statuses=flags)
        if self._error is not None:
            raise self._error
        return self._result


class _Ticket:
    """Per-request future.  Calling it (or ``result(timeout)``) blocks
    until the scheduler hands this request its shared batch, then the
    CALLER performs the batch fetch (idempotent across riders) and
    demuxes its own rows — dispatch on the scheduler thread, fetch on
    the waiter."""

    __slots__ = ("_owner", "_request")

    def __init__(self, owner: "_CoalescerBase", request: _Request):
        self._owner = owner
        self._request = request

    def result(self, timeout: Optional[float] = None):
        req = self._request
        if not req.event.wait(timeout):
            raise TimeoutError("serve ticket not dispatched within timeout")
        return self._owner._demux(req, req.batch.result())

    def __call__(self):
        return self.result()


class _CoalescerBase:
    """The coalescing engine: admission queue + window + one scheduler
    thread + double-buffered dispatch.  Subclasses define how a batch
    launches (``_launch``) and how one request's share of the shared
    result is extracted (``_demux``)."""

    _degrade_empty = False  # subclass: empty-degrade vs re-raise on failure
    _metric_prefix = "pathway_serve_queue"

    def __init__(
        self,
        name: Optional[str] = None,
        window_us: Optional[float] = None,
        max_batch: Optional[int] = None,
        autostart: bool = True,
    ):
        self.name = name or f"serve-{observe.next_id()}"
        # window_us=None -> LIVE registry read per batch window: the
        # online tuner (serve/tuner.py) adjusts ``serve.coalesce_us``
        # while the batcher runs; an explicit window_us pins it
        self._window_pinned = window_us is not None
        self._window_s = (
            coalesce_window_s() if window_us is None else max(0.0, window_us) * 1e-6
        )
        self._max_batch = max_batch or max_batch_queries()
        self._qlock = threading.Lock()
        self._cond = threading.Condition(self._qlock)
        self._queue: Deque[_Request] = deque()
        self._queued_items = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # plain-int stats; the flight recorder samples them at scrape
        # time through the provider registry (zero hot-path cost)
        self.stats: Dict[str, int] = {
            "requests": 0,       # admitted through the queue
            "solo": 0,           # deadline-preempted (or stopped) direct serves
            "batches": 0,        # shared dispatches
            "items": 0,          # queries/items admitted (pre-dedup)
            "items_dispatched": 0,  # unique items actually dispatched
            "dedup_hits": 0,     # duplicate items served from a shared slot
        }
        observe.register_provider(self)
        if autostart:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        with self._cond:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name=f"{self.name}-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop the scheduler thread, draining the queue first — every
        admitted ticket resolves.  Requests submitted after stop serve
        solo on their caller's thread."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        # a submit() that raced the shutdown may have enqueued after the
        # drain loop exited: resolve the stragglers here
        while True:
            reqs = self._pop_batch()
            if not reqs:
                break
            self._dispatch_batch(reqs)

    close = stop

    def __enter__(self) -> "_CoalescerBase":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission ----------------------------------------------------------
    def _admit(
        self, items: Sequence[Any], k: Optional[int], deadline, trace_ctx=None
    ) -> _Ticket:
        req = _Request(items, k, deadline)
        # attach the trace BEFORE the queue sees the request: the
        # scheduler thread may pop and dispatch it immediately, and the
        # link span is recorded from whatever ``r.trace`` holds then
        req.trace = trace_ctx
        if trace_ctx is not None:
            trace_ctx.add_span(
                "admission", trace_ctx.t0_ns, req.t_enqueue_ns,
                items=len(req.items),
            )
        if not req.items:
            req.slots = []
            req.batch = _Batch(lambda: ServeResult(), 0, 1, self._degrade_empty)
            req.event.set()
            return _Ticket(self, req)
        # deadline-preemption rung: a request whose remaining budget is
        # within a few windows of the coalescing wait serves SOLO — the
        # window must never be what pushes a tight serve over budget
        solo = deadline is not None and (
            deadline.remaining_s() <= 4.0 * self._window_s
        )
        with self._cond:
            if solo or not self._running:
                self.stats["solo"] += 1
                self.stats["items"] += len(req.items)
            else:
                self.stats["requests"] += 1
                self.stats["items"] += len(req.items)
                self._queue.append(req)
                self._queued_items += len(req.items)
                self._cond.notify_all()
                return _Ticket(self, req)
        self._dispatch_batch([req], solo=True)
        return _Ticket(self, req)

    # -- scheduler thread ---------------------------------------------------
    def _run(self) -> None:
        prev: Optional[_Batch] = None
        while True:
            reqs: Optional[List[_Request]] = None
            try:
                reqs = self._collect()
                if reqs is None:
                    return
                if reqs:
                    batch = self._dispatch_batch(reqs)
                    if prev is not None:
                        # double buffering: stage-1 of the batch just
                        # dispatched is on the device queue; completing the
                        # PREVIOUS batch's stage 1 and dispatching its
                        # stage 2 now overlaps the two on device
                        prev.advance()
                    prev = batch
            except Exception as exc:
                # the scheduler thread must OUTLIVE any one bad batch:
                # a dead thread would hang every queued and future ticket
                # forever.  Resolve whatever was popped with the error
                # (degrade-or-reraise per subclass policy) and keep going.
                log_once(
                    f"scheduler.run:{type(exc).__name__}",
                    "serve scheduler iteration failed (%r); failing the "
                    "affected batch and continuing",
                    exc,
                )
                for r in reqs or []:
                    if not r.event.is_set():
                        self._resolve_with_error(r, exc)

    def _resolve_with_error(self, req: _Request, exc: BaseException) -> None:
        def handle(_exc: BaseException = exc):
            raise _exc

        if len(req.slots) != len(req.items):
            req.slots = [-1] * len(req.items)
        req.batch = _Batch(handle, len(req.items), 1, self._degrade_empty)
        req.event.set()
        if req.trace is not None:
            # the ticket will raise (or demux a degraded empty); either
            # way this trace's outcome is known — keep it
            trace.finish(req.trace, statuses=("error",))

    def _collect(self) -> Optional[List[_Request]]:
        """Block until work arrives, hold the coalescing window open
        (anchored at the oldest request, capped by every rider's
        deadline slack and the batch query cap), then pop one batch.
        Returns None when stopped and drained."""
        with self._cond:
            while self._running and not self._queue:
                self._cond.wait(0.1)
            if not self._queue:
                return None  # stopped and drained
            anchor_ns = self._queue[0].t_enqueue_ns
            # the cap bounds UNIQUE items (the device batch shape), so the
            # window stays open for hot duplicate-heavy traffic even when
            # the raw queued count is past it — those riders dedup in
            while self._running and self._queued_unique_locked() < self._max_batch:
                now = time.perf_counter_ns()
                if not self._window_pinned:
                    self._window_s = coalesce_window_s()
                end_s = (anchor_ns - now) * 1e-9 + self._window_s
                for r in self._queue:
                    if r.deadline is not None:
                        # the window never eats more than half of any
                        # queued request's remaining budget
                        end_s = min(end_s, 0.5 * r.deadline.remaining_s())
                if end_s <= 0:
                    break
                self._cond.wait(end_s)
            return self._pop_batch_locked()

    def _pop_batch(self) -> List[_Request]:
        with self._cond:
            return self._pop_batch_locked()

    def _queued_unique_locked(self) -> int:
        try:
            return len({it for r in self._queue for it in r.items})
        except TypeError:
            # unhashable items cannot dedup: fall back to the raw count
            # (the bad request itself fails downstream in _dispatch_batch)
            return self._queued_items

    def _pop_batch_locked(self) -> List[_Request]:
        # the cap bounds UNIQUE items (the device batch shape): duplicate
        # queries dedup into an existing slot, so hot requests keep
        # riding a batch that is already full of their text
        take: List[_Request] = []
        seen: set = set()
        while self._queue:
            r = self._queue[0]
            try:
                fresh = sum(1 for it in r.items if it not in seen)
            except TypeError:
                fresh = len(r.items)  # unhashable: counts as all-fresh
            if take and len(seen) + fresh > self._max_batch:
                break
            take.append(self._queue.popleft())
            self._queued_items -= len(r.items)
            try:
                seen.update(r.items)
            except TypeError:
                pass  # the request still dispatches; dedup just skips it
        return take

    # -- dispatch -----------------------------------------------------------
    def _dispatch_batch(self, reqs: List[_Request], solo: bool = False) -> _Batch:
        """Pack one shared batch (sorted-unique items — deterministic
        composition regardless of arrival order), launch it, and hand
        the batch to every rider.  Every ticket resolves no matter what
        the launch does.  ``solo`` dispatches (deadline preemption,
        stopped scheduler) skip the coalescing counters — ``batches``
        counts shared-window dispatches only."""
        items: List[Any] = []
        total = sum(len(r.items) for r in reqs)
        error: Optional[BaseException] = None
        # one BATCH trace for the shared work, linked from every traced
        # rider: sampling already happened at the riders' admission, so
        # the batch trace is created iff a traced rider is aboard
        bctx = None
        if any(r.trace is not None for r in reqs):
            bctx = trace.start_trace(
                "serve.batch",
                deadline=self._batch_deadline(reqs),
                kind="batch",
                sample=False,
            )
            if bctx is not None:
                bctx.annotate(
                    scheduler=self.name, riders=len(reqs), solo=bool(solo)
                )
        try:
            index: Dict[Any, int] = {}
            for r in reqs:
                for it in r.items:
                    if it not in index:
                        index[it] = -1
                        items.append(it)
            items.sort()
            for i, it in enumerate(items):
                index[it] = i
            for r in reqs:
                r.slots = [index[it] for it in r.items]
            if bctx is not None:
                bctx.annotate(items=len(items), deduped=total - len(items))
                with trace.use(bctx):
                    handle = self._launch(items, reqs)
            else:
                handle = self._launch(items, reqs)
        except Exception as exc:
            # packing or launch failed: every ticket still resolves —
            # the error lands in _Batch.result() (degrade or re-raise)
            error = exc
            for r in reqs:
                if len(r.slots) != len(r.items):
                    r.slots = [-1] * len(r.items)

            def handle(_exc: BaseException = error):
                raise _exc
        batch = _Batch(
            handle, len(items), len(reqs), self._degrade_empty, trace_ctx=bctx
        )
        with self._qlock:
            if not solo:
                self.stats["batches"] += 1
            self.stats["items_dispatched"] += len(items)
            self.stats["dedup_hits"] += total - len(items)
        t_now = time.perf_counter_ns()
        for r in reqs:
            _H_QUEUE_WAIT.observe_ns(t_now - r.t_enqueue_ns)
            rt = r.trace
            if rt is not None:
                # the rider's LINK span: its duration is the queue wait
                # (enqueue → handoff, the EXACT interval _H_QUEUE_WAIT
                # just observed — exemplar and observation must land in
                # the same bucket), its attrs say which batch it rode
                # and with how many others; /traces inlines the linked
                # batch tree under it.
                if bctx is not None:
                    rt.add_link(bctx.trace_id)
                    rt.add_span(
                        "batch", r.t_enqueue_ns, t_now,
                        exemplar=_H_QUEUE_WAIT,
                        linked_trace=bctx.trace_id,
                        riders=len(reqs), batch_items=len(items),
                        solo=bool(solo),
                    )
                else:
                    rt.add_span(
                        "batch", r.t_enqueue_ns, t_now,
                        exemplar=_H_QUEUE_WAIT,
                        riders=len(reqs), solo=bool(solo),
                    )
            r.batch = batch
            r.event.set()
        return batch

    @staticmethod
    def _batch_deadline(reqs: List[_Request]):
        """The shared dispatch runs under the MOST generous rider's
        deadline (None if any rider has none): a coalesced request
        accepted the window's cost at admission, and killing the whole
        batch on the tightest budget would fail its patient riders."""
        deadline = None
        for r in reqs:
            if r.deadline is None:
                return None
            if deadline is None or r.deadline.remaining_s() > deadline.remaining_s():
                deadline = r.deadline
        return deadline

    # -- subclass hooks -----------------------------------------------------
    def _launch(self, items: List[Any], reqs: List[_Request]):
        raise NotImplementedError

    def _demux(self, req: _Request, batch_result):
        raise NotImplementedError

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        labels = {"scheduler": self.name}
        yield ("gauge", f"{self._metric_prefix}_depth", labels, len(self._queue))
        for mode in ("requests", "solo"):
            yield (
                "counter",
                f"{self._metric_prefix}_requests_total",
                {**labels, "mode": "coalesced" if mode == "requests" else mode},
                self.stats[mode],
            )
        yield ("counter", f"{self._metric_prefix}_batches_total", labels, self.stats["batches"])
        for kind, key in (
            ("admitted", "items"),
            ("dispatched", "items_dispatched"),
            ("deduped", "dedup_hits"),
        ):
            yield (
                "counter",
                f"{self._metric_prefix}_queries_total",
                {**labels, "kind": kind},
                self.stats[key],
            )


class _ReplicaHandle:
    """Completion wrapper that releases its replica's in-flight slot
    exactly once, whether the batch completes, fails, or is advanced
    first — the placement layer's load signal must drain even when the
    degradation ladder swallows the failure."""

    __slots__ = ("_handle", "_release", "_released", "_rlock")

    def __init__(self, handle, release):
        self._handle = handle
        self._release = release
        self._released = False
        self._rlock = threading.Lock()

    def advance(self) -> None:
        adv = getattr(self._handle, "advance", None)
        if adv is not None:
            adv()

    def _release_once(self) -> None:
        with self._rlock:
            if self._released:
                return
            self._released = True
        self._release()

    def __call__(self):
        try:
            return self._handle()
        finally:
            self._release_once()


class ServeScheduler(_CoalescerBase):
    """Coalescing front-end for the retrieve(→rerank) serve path.

    ``target`` is a ``RetrieveRerankPipeline`` or ``FusedEncodeSearch``
    (anything with ``submit(texts, k, deadline=...) -> completion``).
    Concurrent ``serve()``/``submit()`` calls coalesce into ONE shared
    stage-1 batch at the existing bucket shapes; per-request ``k`` is
    honored by truncating the shared top-``max(k)`` rows, and per-request
    results carry the batch's degradation flags (a stage-1 failure
    degrades exactly the riders of that batch).

    **Generation-keyed dedup**: the in-window dedup key is
    ``(text, index_generation)``, not the text alone — an absorb/retrain
    landing inside an open coalescing window bumps the target index's
    generation, so a later duplicate admits into its OWN slot instead of
    sharing one dispatched against the pre-mutation index state.

    **Replica placement**: ``replicas`` adds data-parallel serve targets
    (each a full pipeline over its own device group) behind this ONE
    shared admission queue.  Each coalesced batch is assigned to the
    least-loaded replica (in-flight batches, ties rotated), so a slow
    or recovering replica sheds load automatically; per-replica
    queue-depth gauges and placement counters export on the scrape
    surface (``pathway_serve_replica_*``).

    **Tier-0 result cache** (``pathway_tpu/cache``): before admission,
    the request's rows are looked up under ``(text, index generation,
    k)`` — a full hit resolves the ticket immediately: no coalescing
    window, ZERO device dispatches, bit-identical to the serve that
    populated the entry.  Rows are captured at demux (on the waiter's
    thread, off every scheduler lock) only for CLEAN results whose
    dispatch-time generation matches the admission generation, so an
    absorb/retrain/remove — which bumps the index generation — makes
    every stale entry structurally unreachable.  ``result_cache`` is an
    explicit ``ResultCache``, ``"auto"`` (the default: built from the
    ``PATHWAY_CACHE[_RESULT]*`` env knobs), or ``None`` to disable.
    """

    _degrade_empty = True

    def __init__(
        self,
        target,
        k: Optional[int] = None,
        name: Optional[str] = None,
        window_us: Optional[float] = None,
        max_batch: Optional[int] = None,
        autostart: bool = True,
        replicas: Optional[Sequence[Any]] = None,
        result_cache: Any = "auto",
    ):
        self.target = target
        self.k = k or getattr(target, "k", 10)
        self._result_cache = (
            result_cache_from_env() if result_cache == "auto" else result_cache
        )
        # data-parallel replica set: the placement layer spreads batches
        # over [target, *replicas]; a single-target scheduler is the
        # degenerate one-replica case with zero extra cost
        self._replicas: List[Any] = [target] + list(replicas or ())
        self._inflight: List[int] = [0] * len(self._replicas)
        self._placed: List[int] = [0] * len(self._replicas)
        gen_fn = getattr(target, "index_generation", None)
        self._generation = gen_fn if callable(gen_fn) else None
        try:
            params = inspect.signature(target.submit).parameters
        except (TypeError, ValueError):
            params = {}
        self._submit_n_requests = "n_requests" in params
        self._submit_deadline = "deadline" in params
        super().__init__(
            name=name, window_us=window_us, max_batch=max_batch, autostart=autostart
        )
        self.stats.setdefault("cache_hits", 0)

    # -- public serve surface ----------------------------------------------
    def submit(
        self,
        texts: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
        priority: Optional[str] = None,
    ) -> _Ticket:
        """Admit one serve request; returns a ticket (zero-arg callable /
        ``result(timeout)``) resolving to this request's ``ServeResult``.
        ``deadline`` defaults to the target's own policy
        (``deadline_ms``/``PATHWAY_SERVE_DEADLINE_MS``); a deadline too
        tight for the coalescing window serves solo immediately.
        ``priority`` (high/normal/low; default ``serve.default_priority``)
        is the load-shedding class — shed-class requests get an empty
        ``load_shed``-flagged result while a shed-enabled SLO burns."""
        if deadline is None:
            default = getattr(self.target, "_default_deadline", Deadline.from_env)
            deadline = default()
        if priority is None:
            priority = config.get("serve.default_priority")
        priority = str(priority).lower()
        # SLO-burn load shedding (observe/slo.py): while a shed-enabled
        # objective (serve latency/availability, ingest freshness) burns
        # past threshold, shed-class requests are turned away AT
        # admission — an immediately-resolved ticket carrying an empty
        # ``load_shed``-flagged ServeResult (counted, flagged, never an
        # exception), zero dispatches, no window wait.  The probe is a
        # throttled cached read and may never fail or slow an admission.
        # ``PATHWAY_SERVE_SHED=0`` restores the round-15 advisory-only
        # behavior (log + count, admit normally).
        if slo_mod.should_shed():
            if config.get("serve.shed") and priority in _shed_classes():
                c = _C_SHED.get(priority)
                if c is None:
                    c = observe.counter(
                        "pathway_serve_shed_total", priority=priority
                    )
                c.inc()
                record_degraded(LOAD_SHED, 1)
                with self._qlock:
                    self.stats["shed"] = self.stats.get("shed", 0) + 1
                ctx = trace.start_trace("serve.request", deadline=deadline)
                if ctx is not None:
                    ctx.annotate(priority=priority, shed=True)
                req = _Request(list(texts), k or self.k, deadline)
                req.trace = ctx
                req.slots = list(range(len(texts)))
                shed = ServeResult(
                    [[] for _ in texts],
                    degraded=(LOAD_SHED,),
                    meta={"priority": priority, "shed": True},
                )
                req.batch = _Batch(
                    lambda: shed, len(texts), 1, self._degrade_empty
                )
                req.event.set()
                return _Ticket(self, req)
            log_once(
                "serve.slo_shed",
                "SLO burn-rate alert firing: should_shed() advises "
                "load shedding (advisory only — PATHWAY_SERVE_SHED off "
                "or priority not shed-class; see GET /slo)",
            )
            slo_mod.record_shed_advised()
        # per-request trace root (observe/trace.py): admission → cache →
        # batch link → demux all hang off this context; None (one flag
        # check, no allocation) when tracing is off or sampled out
        ctx = trace.start_trace("serve.request", deadline=deadline)
        gen = 0
        if self._generation is not None:
            try:
                gen = normalize_generation(self._generation())
            except Exception:
                gen = 0
        # dedup item = (text, generation-at-admission): only duplicates
        # that observed the SAME index state may share a dispatched slot.
        # The SAME helper derives the result-cache key (cache/keys.py),
        # so the two spellings can never drift.  Against a PARTITIONED
        # fabric ``gen`` is the fleet generation VECTOR — an absorb on
        # ANY partition changes it, so a result cached via host A can
        # never be served after host B's absorb.
        items = [query_key(t, gen) for t in texts]
        k_eff = k or self.k
        cache = self._result_cache
        if cache is not None and items:
            # tier-0 lookup BEFORE admission (and before any scheduler
            # lock): a full hit is a zero-dispatch serve that skips the
            # coalescing window entirely; any miss (or cache failure)
            # falls through to the shared batch unchanged
            if ctx is not None:
                t_c0 = time.perf_counter_ns()
                with trace.use(ctx):  # tier events annotate this trace
                    rows = cache.get_rows(items, k_eff, deadline=deadline)
                ctx.add_span(
                    "cache.result", t_c0, time.perf_counter_ns(),
                    status="hit" if rows is not None else "miss",
                    items=len(items),
                )
            else:
                rows = cache.get_rows(items, k_eff, deadline=deadline)
            if rows is not None:
                with self._qlock:
                    self.stats["cache_hits"] = (
                        self.stats.get("cache_hits", 0) + 1
                    )
                    self.stats["items"] += len(items)
                req = _Request(items, k_eff, deadline)
                req.trace = ctx
                if ctx is not None:
                    ctx.annotate(cache="hit")
                req.slots = list(range(len(items)))
                hit = ServeResult(rows)
                req.batch = _Batch(
                    lambda: hit, len(items), 1, self._degrade_empty
                )
                req.event.set()
                return _Ticket(self, req)
        ticket = self._admit(items, k_eff, deadline, trace_ctx=ctx)
        if cache is not None:
            ticket._request.cache_store = True
        return ticket

    def serve(
        self,
        texts: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
        priority: Optional[str] = None,
    ) -> ServeResult:
        return self.submit(texts, k, deadline=deadline, priority=priority)()

    __call__ = serve

    # -- replica placement --------------------------------------------------
    def _pick_replica(self) -> int:
        """Least-loaded replica (in-flight batches), ties rotated by
        lifetime placement count so an idle fleet round-robins instead
        of hammering replica 0."""
        with self._qlock:
            r = min(
                range(len(self._replicas)),
                key=lambda i: (self._inflight[i], self._placed[i], i),
            )
            self._inflight[r] += 1
            self._placed[r] += 1
            return r

    def _release_replica(self, r: int) -> None:
        with self._qlock:
            self._inflight[r] = max(0, self._inflight[r] - 1)

    # -- engine hooks -------------------------------------------------------
    def _launch(self, items: List[Tuple[str, int]], reqs: List[_Request]):
        k_batch = max((r.k or self.k) for r in reqs)
        deadline = self._batch_deadline(reqs)
        kwargs: Dict[str, Any] = {}
        if self._submit_deadline:
            kwargs["deadline"] = deadline
        if self._submit_n_requests:
            # per-request degradation accounting: a stage-1 failure in
            # this batch flags + counts every rider, not "one batch"
            kwargs["n_requests"] = len(reqs)
        # composition stays deterministic: items are the sorted-unique
        # (text, gen) pairs, so the text list the target sees is sorted
        # (a text straddling a generation bump appears once per gen —
        # same results, separate slots)
        texts = [t for t, _gen in items]
        r = self._pick_replica()
        try:
            handle = self._replicas[r].submit(texts, k_batch, **kwargs)
        except BaseException:
            self._release_replica(r)
            raise
        return _ReplicaHandle(handle, lambda: self._release_replica(r))

    def _demux(self, req: _Request, batch_result) -> ServeResult:
        k = req.k or self.k
        rows = []
        for slot in req.slots:
            row = (
                batch_result[slot]
                if 0 <= slot < len(batch_result)
                else []
            )
            rows.append(list(row[:k]))
        result = ServeResult(
            rows,
            degraded=tuple(getattr(batch_result, "degraded", ())),
            meta=getattr(batch_result, "meta", None),
        )
        cache = self._result_cache
        if cache is not None and req.cache_store and not result.degraded:
            # tier-0 capture, on the WAITER's thread off every scheduler
            # lock.  Clean results only (a cached degraded serve would
            # pin a transient outage for a TTL), and only when the
            # dispatch-time generation the serve path stamped into the
            # result matches this item's admission generation — a
            # mutation landing mid-flight must not be stored under the
            # pre-mutation key.
            meta_gen = result.meta.get("index_generation")
            ctx = req.trace
            with trace.use(ctx) if ctx is not None else _NOOP_CM:
                for (text, gen), row in zip(req.items, rows):
                    if meta_gen is not None and (
                        normalize_generation(meta_gen)
                        != normalize_generation(gen)
                    ):
                        continue
                    cache.put_row(text, gen, k, row, deadline=req.deadline)
        ctx = req.trace
        if ctx is not None:
            # rider trace complete: the root span IS the request latency
            # (admission → demux); tail sampling runs now, when the
            # outcome (rungs, deadline, duration percentile) is known
            ctx.annotate(k=k)
            trace.finish(ctx, statuses=tuple(result.degraded))
        return result

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        yield from super().observe_metrics()
        labels = {"scheduler": self.name}
        if self._result_cache is not None:
            # requests resolved entirely from the tier-0 result cache
            # (zero-dispatch serves); per-tier hit/miss/bytes render
            # from the cache's own provider (pathway_cache_*)
            yield (
                "counter",
                "pathway_serve_queue_requests_total",
                {**labels, "mode": "cached"},
                self.stats.get("cache_hits", 0),
            )
        for r in range(len(self._replicas)):
            rl = {**labels, "replica": str(r)}
            yield (
                "gauge", "pathway_serve_replica_depth", rl, self._inflight[r]
            )
            yield (
                "counter",
                "pathway_serve_replica_batches_total",
                rl,
                self._placed[r],
            )


class SharedBatcher(_CoalescerBase):
    """The same coalescing engine for flat scoring calls: concurrent
    ``score(items)`` calls (e.g. (query, doc) pairs from QA dataflow
    rows) coalesce into ONE ``submit_fn(items, deadline=...)`` dispatch;
    per-call scores demux (and dedup) from the shared result.  A batch
    failure re-raises to every rider — the caller owns its ladder (the
    QA rerank path already converts scoring failures into
    ``rerank_skipped``)."""

    _degrade_empty = False

    def __init__(
        self,
        submit_fn,
        name: Optional[str] = None,
        window_us: Optional[float] = None,
        max_batch: Optional[int] = None,
        autostart: bool = True,
    ):
        self._submit_fn = submit_fn
        try:
            params = inspect.signature(submit_fn).parameters
        except (TypeError, ValueError):
            params = {}
        self._submit_deadline = "deadline" in params
        super().__init__(
            name=name or f"batch-{observe.next_id()}",
            window_us=window_us, max_batch=max_batch, autostart=autostart,
        )

    def submit(
        self, items: Sequence[Any], deadline: Optional[Deadline] = None
    ) -> _Ticket:
        return self._admit(list(items), None, deadline)

    def score(
        self, items: Sequence[Any], deadline: Optional[Deadline] = None
    ) -> np.ndarray:
        return self.submit(items, deadline=deadline)()

    __call__ = score

    def _launch(self, items: List[Any], reqs: List[_Request]):
        deadline = self._batch_deadline(reqs)
        if self._submit_deadline:
            return self._submit_fn(items, deadline=deadline)
        return self._submit_fn(items)

    def _demux(self, req: _Request, batch_result) -> np.ndarray:
        flat = np.asarray(batch_result)
        return np.asarray(
            [flat[slot] for slot in req.slots], dtype=flat.dtype
        )
