"""Durable warm-state snapshots — replica bring-up without re-ingest.

A serve replica's *warm state* is everything the cold path would have to
recompute before it serves at full quality: the IVF resident + tail
slabs (ops/ivf.py ``warm_state``), the compressed forward-index row
buckets (index/forward.py), and the result / embedding cache tiers
(cache/result.py, cache/embedding.py).  ``WarmStateManager`` writes
generation-stamped snapshots of those components to a persistence
backend (persistence/backends.py) and restores them at bring-up, so a
replacement host in the serve fabric (serve/fabric.py) joins the
replica group in seconds instead of re-ingesting the corpus.

Durability discipline (the same rules as the engine snapshot log):

- **Chunked, CRC-framed blobs.**  Each component section pickles to one
  byte string, split into ``PATHWAY_WARMSTATE_CHUNK_BYTES`` chunks, each
  wrapped in a ``persistence/framing.py`` frame.  A torn write or bit
  rot fails the CRC scan on restore — a corrupt snapshot is DETECTED,
  never installed.
- **Manifest-last commit.**  Section blobs are written first; the
  ``MANIFEST`` key (chunk counts + byte totals + per-section
  generations) is written LAST.  A crash mid-snapshot leaves no
  manifest, so the half-written snapshot is invisible to restore.
- **Degrade, never fail.**  A faulted snapshot (chaos site
  ``warmstate.snapshot``) is a SKIPPED cadence counted on
  ``pathway_warmstate_snapshot_skipped_total`` — the serve tier never
  pays for its own durability.  A failed restore (CRC, truncation,
  missing blob, unpickle error, geometry mismatch at install — chaos
  site ``warmstate.restore``) is counted per-kind on
  ``pathway_warmstate_restore_failures_total{kind}``, falls back to the
  next-older snapshot, and ultimately degrades to a FLAGGED cold start:
  the caller re-ingests; the index is never wrong.
- **Bit-identity.**  A restored component carries the writer's
  ``generation``, so a warm-restored replica serves bit-identically to
  the snapshot writer at that generation and its cache/dedup keys
  (cache/keys.py) agree across the fabric.

Cross-host agreement: after restore, ``agree_generation`` runs the
coordinator's generation through ``parallel/distributed.broadcast_obj``
so every host in a replica group serves the same index generation; a
degraded control plane (chaos site ``dist.broadcast``) yields flagged
local-only agreement, never a hung bring-up.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import observe
from .. import config
from ..cache.keys import normalize_generation
from ..parallel import distributed as dist
from ..persistence.framing import frame, scan
from ..robust import inject, log_once

__all__ = ["RestoreReport", "WarmStateManager"]

_MANIFEST = "MANIFEST"

# counter caches (tiny label sets, same idiom as robust/retry.py)
_restore_fail_counters: Dict[str, observe.Counter] = {}


def _count_restore_failure(kind: str) -> None:
    c = _restore_fail_counters.get(kind)
    if c is None:
        c = _restore_fail_counters[kind] = observe.counter(
            "pathway_warmstate_restore_failures_total", kind=kind
        )
    c.inc()


_snapshots_total = observe.counter("pathway_warmstate_snapshots_total")
_snapshot_skipped = observe.counter("pathway_warmstate_snapshot_skipped_total")
_restores_warm = observe.counter(
    "pathway_warmstate_restores_total", outcome="warm"
)
_restores_cold = observe.counter(
    "pathway_warmstate_restores_total", outcome="cold"
)


class RestoreReport:
    """What a bring-up restore actually did — the FLAG half of the
    degrade-never-fail contract.  ``restored`` False means cold start:
    the caller re-ingests (and the failure kinds were counted)."""

    __slots__ = ("restored", "snapshot", "generations", "sections", "reasons")

    def __init__(
        self,
        restored: bool,
        snapshot: Optional[str],
        generations: Dict[str, int],
        sections: Dict[str, str],
        reasons: Tuple[str, ...],
    ):
        self.restored = restored
        self.snapshot = snapshot  # key prefix of the snapshot installed
        self.generations = generations  # section -> restored generation
        self.sections = sections  # section -> "restored" | "failed:<kind>"
        self.reasons = reasons  # degradation reasons, deduped, ordered

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"RestoreReport(restored={self.restored}, "
            f"snapshot={self.snapshot!r}, sections={self.sections})"
        )


class WarmStateManager:
    """Snapshot/restore driver over named components.

    ``components`` maps a section name to any object exposing the
    warm-state pair — ``warm_state() -> dict`` (picklable) and
    ``load_warm_state(state)`` (raises on mismatch).  The IVF index,
    the forward index, and both cache tiers all implement it; a serve
    stack registers whichever subset it owns::

        mgr = WarmStateManager(backend, name="replica0", components={
            "ivf": index, "forward": fwd, "result_cache": rc,
        })
        mgr.snapshot()            # on the maintenance cadence
        report = mgr.restore()    # at bring-up; .restored False = cold

    Thread-safety: one lock serializes snapshot/restore/prune — the
    cadence thread and an operator-triggered snapshot must not
    interleave their key writes.
    """

    def __init__(
        self,
        backend,
        *,
        name: str = "default",
        prefix: str = "warmstate",
        components: Optional[Dict[str, Any]] = None,
        chunk_bytes: Optional[int] = None,
        interval_s: Optional[float] = None,
        keep: Optional[int] = None,
    ):
        self.backend = backend
        self.name = str(name)
        self.prefix = str(prefix).strip("/")
        self.components: Dict[str, Any] = dict(components or {})
        self.chunk_bytes = int(
            chunk_bytes
            if chunk_bytes is not None
            else config.get("warmstate.chunk_bytes")
        )
        self.interval_s = float(
            interval_s
            if interval_s is not None
            else config.get("warmstate.interval_s")
        )
        self.keep = int(
            keep if keep is not None else config.get("warmstate.keep")
        )
        self._lock = threading.Lock()
        self._last_snapshot_mono: Optional[float] = None
        self.stats: Dict[str, int] = {
            "snapshots": 0,
            "snapshot_skipped": 0,
            "restores_warm": 0,
            "restores_cold": 0,
            "pruned": 0,
        }

    # -- key layout ----------------------------------------------------------
    def _root(self) -> str:
        return f"{self.prefix}/{self.name}"

    def _snap_prefix(self, seq: int) -> str:
        return f"{self._root()}/snap-{seq:012d}"

    def _list_seqs(self) -> List[int]:
        """Committed snapshot sequence numbers (manifest present),
        ascending.  Uncommitted snapshot directories are invisible."""
        root = self._root() + "/"
        seqs = []
        for key in self.backend.list_keys(root):
            rel = key[len(root):]
            parts = rel.split("/")
            if len(parts) == 2 and parts[1] == _MANIFEST:
                snap = parts[0]
                if snap.startswith("snap-"):
                    try:
                        seqs.append(int(snap[len("snap-"):]))
                    except ValueError:
                        continue
        return sorted(set(seqs))

    # -- snapshot ------------------------------------------------------------
    def snapshot(self, deadline=None) -> Optional[str]:
        """Write one generation-stamped snapshot of every registered
        component.  Returns the snapshot key prefix, or None when the
        cadence was SKIPPED (chaos site ``warmstate.snapshot``, backend
        error) — counted, logged once, never raised: durability must
        not fail a serve tier."""
        with self._lock:
            try:
                inject.fire("warmstate.snapshot", deadline=deadline)
                return self._snapshot_locked()
            except Exception as exc:
                _snapshot_skipped.inc()
                self.stats["snapshot_skipped"] += 1
                log_once(
                    f"warmstate.snapshot:{type(exc).__name__}",
                    "warm-state snapshot skipped (%r); next cadence retries",
                    exc,
                )
                return None

    def _snapshot_locked(self) -> str:
        seqs = self._list_seqs()
        seq = (seqs[-1] + 1) if seqs else 0
        prefix = self._snap_prefix(seq)
        manifest: Dict[str, Any] = {"seq": seq, "sections": {}}
        for section, component in self.components.items():
            state = component.warm_state()
            payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
            chunks = [
                payload[o: o + self.chunk_bytes]
                for o in range(0, max(len(payload), 1), self.chunk_bytes)
            ]
            blob = b"".join(frame(c) for c in chunks)
            self.backend.put(f"{prefix}/{section}", blob)
            manifest["sections"][section] = {
                "chunks": len(chunks),
                "bytes": len(payload),
                "generation": (
                    int(state["generation"])
                    if isinstance(state, dict) and "generation" in state
                    else None
                ),
            }
        # manifest LAST: its presence is the commit marker — a crash
        # before this put leaves the snapshot invisible to restore
        self.backend.put(
            f"{prefix}/{_MANIFEST}",
            frame(pickle.dumps(manifest, protocol=pickle.HIGHEST_PROTOCOL)),
        )
        _snapshots_total.inc()
        self.stats["snapshots"] += 1
        self._last_snapshot_mono = time.monotonic()
        self._prune_locked()
        return prefix

    def maybe_snapshot(self, deadline=None) -> Optional[str]:
        """Cadence entry (call from a maintenance loop): snapshots when
        ``PATHWAY_WARMSTATE_INTERVAL_S`` has elapsed since the last one
        (0 = manual only)."""
        if self.interval_s <= 0:
            return None
        last = self._last_snapshot_mono
        if last is not None and time.monotonic() - last < self.interval_s:
            return None
        return self.snapshot(deadline=deadline)

    def _prune_locked(self) -> None:
        """Best-effort: drop all but the newest ``keep`` committed
        snapshots (manifest deleted FIRST so a partially pruned snapshot
        is invisible, mirroring the commit order)."""
        seqs = self._list_seqs()
        for seq in seqs[: max(0, len(seqs) - self.keep)]:
            prefix = self._snap_prefix(seq)
            self.backend.delete(f"{prefix}/{_MANIFEST}")
            for key in self.backend.list_keys(prefix + "/"):
                self.backend.delete(key)
            self.stats["pruned"] += 1

    # -- restore ---------------------------------------------------------------
    def restore(self, deadline=None) -> RestoreReport:
        """Bring-up: install the newest intact snapshot into the
        registered components.  Walks snapshots newest→oldest; every
        failure (CRC, truncation, missing section, unpickle, install
        mismatch, chaos site ``warmstate.restore``) is counted on
        ``pathway_warmstate_restore_failures_total{kind}`` and falls
        back to the next-older snapshot.  When none restores, the
        report degrades to a FLAGGED cold start (``restored=False``) —
        the caller re-ingests; a wrong index is never installed.

        An install failure mid-snapshot may leave earlier sections
        installed; the next-older attempt re-installs EVERY section, so
        any successful restore is internally consistent.  Only the
        terminal cold-start path can leave a partial install, and there
        the caller's re-ingest rebuilds all components anyway."""
        reasons: List[str] = []
        try:
            inject.fire("warmstate.restore", deadline=deadline)
        except Exception as exc:
            _count_restore_failure("injected")
            reasons.append("warm_restore_failed")
            log_once(
                f"warmstate.restore:{type(exc).__name__}",
                "warm-state restore degraded to cold start (%r)",
                exc,
            )
            _restores_cold.inc()
            self.stats["restores_cold"] += 1
            return RestoreReport(False, None, {}, {}, tuple(reasons))
        with self._lock:
            for seq in reversed(self._list_seqs()):
                prefix = self._snap_prefix(seq)
                ok, generations, sections = self._restore_one(prefix)
                if ok:
                    _restores_warm.inc()
                    self.stats["restores_warm"] += 1
                    return RestoreReport(
                        True, prefix, generations, sections, tuple(reasons)
                    )
                reasons.append("warm_restore_failed")
        _restores_cold.inc()
        self.stats["restores_cold"] += 1
        if not reasons:
            # nothing durable yet — a first boot is a clean cold start,
            # not a failure (nothing counted)
            return RestoreReport(False, None, {}, {}, ())
        return RestoreReport(False, None, {}, {}, tuple(dict.fromkeys(reasons)))

    def _restore_one(
        self, prefix: str
    ) -> Tuple[bool, Dict[str, int], Dict[str, str]]:
        """Try one committed snapshot: decode EVERY section first (CRC +
        chunk count + unpickle), install second — a corrupt blob is
        rejected before any component mutates."""
        sections: Dict[str, str] = {}
        generations: Dict[str, int] = {}
        manifest = self._read_manifest(prefix)
        if manifest is None:
            _count_restore_failure("manifest")
            return False, {}, {}
        decoded: Dict[str, Any] = {}
        for section in self.components:
            entry = manifest["sections"].get(section)
            if entry is None:
                _count_restore_failure("missing")
                sections[section] = "failed:missing"
                return False, {}, sections
            blob = self.backend.get(f"{prefix}/{section}")
            if blob is None:
                _count_restore_failure("missing")
                sections[section] = "failed:missing"
                return False, {}, sections
            payloads, intact = scan(blob)
            if not intact or len(payloads) != int(entry["chunks"]):
                _count_restore_failure("crc" if not intact else "truncated")
                sections[section] = "failed:crc"
                return False, {}, sections
            payload = b"".join(payloads)
            if len(payload) != int(entry["bytes"]):
                _count_restore_failure("truncated")
                sections[section] = "failed:truncated"
                return False, {}, sections
            try:
                decoded[section] = pickle.loads(payload)
            except Exception:
                _count_restore_failure("unpickle")
                sections[section] = "failed:unpickle"
                return False, {}, sections
        for section, component in self.components.items():
            try:
                component.load_warm_state(decoded[section])
            except Exception as exc:
                _count_restore_failure("install")
                sections[section] = "failed:install"
                log_once(
                    f"warmstate.install:{section}:{type(exc).__name__}",
                    "warm-state install failed for %r (%r); "
                    "trying older snapshot",
                    section,
                    exc,
                )
                return False, {}, sections
            sections[section] = "restored"
            gen = manifest["sections"][section].get("generation")
            if gen is not None:
                generations[section] = int(gen)
        return True, generations, sections

    def _read_manifest(self, prefix: str) -> Optional[Dict[str, Any]]:
        blob = self.backend.get(f"{prefix}/{_MANIFEST}")
        if blob is None:
            return None
        payloads, intact = scan(blob)
        if not intact or len(payloads) != 1:
            return None
        try:
            manifest = pickle.loads(payloads[0])
        except Exception:
            return None
        if not isinstance(manifest, dict) or "sections" not in manifest:
            return None
        return manifest

    # -- cross-host agreement --------------------------------------------------
    def agree_generation(
        self, local_gen, *, tag: str, deadline=None
    ):
        """Replica-group index-generation agreement: the coordinator's
        generation broadcast to every host (``name`` is unique per
        bring-up ``tag``).  Returns ``(generation, agreed)`` —
        ``agreed`` False means the control plane DEGRADED (counted on
        ``pathway_dist_degraded_total{site="broadcast"}``) and this
        host proceeds on its local generation, flagged by the caller;
        bring-up is never hung on the coordination service.

        ``local_gen`` may be a scalar (replica fleet) or a generation
        VECTOR — one entry per partition (``cache/keys.py``
        ``normalize_generation`` spells both) — so a partitioned fleet
        agrees on every partition's generation at once and front-side
        cache keys derived from the agreed vector stay sound fleet-wide."""
        local = normalize_generation(local_gen)
        value = dist.broadcast_obj(
            local if dist.is_coordinator() else None,
            name=f"warmstate/{self.name}/gen/{tag}",
            deadline=deadline,
        )
        if value is None:
            return local, False
        value = normalize_generation(value)
        return value, bool(value == local)
