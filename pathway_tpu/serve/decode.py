"""Continuous token-level batching for generator decode.

The serve tier made retrieval fast; generation is the next bottleneck
("Accelerating Retrieval-Augmented Generation", arxiv 2412.15246:
once retrieval is cached and batched, the LLM decode dominates
end-to-end latency), and the listwise-rerank workload the cascade's LLM
stage will issue (RankLLM, arxiv 2505.19284) is many SHORT, shared-
prefix generations — exactly what call-granular batching wastes:
concurrent ``generate()`` calls serialize into separate decode scans,
and every prompt in a batch pays the full ``steps`` budget even after
emitting EOS.

``ContinuousDecoder`` batches at TOKEN granularity instead:

- a persistent device-resident **slot pool** — per-layer K/V buffers
  ``[slots, L, H, T, d]`` plus per-slot rng chains — outlives any one
  request (``models/transformer.py SlotKVDecoder``, the params-
  compatible twin whose step advances only active slots);
- requests **JOIN** the step loop after a bucketed prefill
  (``TextGenerator._slot_prefill_fn``; shared-prefix prompts ride
  ``PrefixKVCache`` blocks and prefill only their tails) and **LEAVE**
  at EOS or budget exhaustion, freeing their slot for the next queued
  request mid-flight;
- the loop advances every active slot together in
  ``PATHWAY_DECODE_STEP_BUCKET``-step chunks — ONE compiled dispatch
  per chunk regardless of how many requests ride it (ONE compile
  signature per engine: the step shapes are (slots, T, chunk), all
  static).

**Token identity.**  Every request decoded through the pool yields
exactly the tokens of a solo ``generate([prompt])`` at the same seed —
regardless of join order, batch-mates, or which slot it lands in:

- each slot samples with its OWN rng chain (``PRNGKey(seed)``, one
  split per emitted token — the solo chain; a batch-level chain would
  make tokens depend on batch composition);
- masked K/V attention is width-invariant: key slots past a row's
  frontier carry exact-zero probability, so the pool's fixed buffer
  width ``T`` reproduces the solo decode's prompt+steps-wide buffer
  bit-for-bit;
- a reused slot cannot alias its previous occupant: a joining prefill
  (re)writes every position the request will ever attend, and inactive
  lanes' buffers are bit-frozen by ``SlotKVDecoder``'s select.

**Speculative decode** (``PATHWAY_DECODE_SPEC_K`` ≥ 2): instead of one
token per pool step, each round drafts ``k-1`` proposal tokens per
active slot — mined host-side from the slot's OWN context (prompt +
emitted tokens: RAG prompts quote their retrieved passages, so the
generation frequently re-walks n-grams the prompt already contains),
falling back to a reduced-layer trunk dispatch over the same params
(``TextGenerator._slot_draft_fn``) — then ONE batched verify dispatch
(``_slot_verify_fn``) scores all ``k`` positions pool-wide and accepts
each lane's longest agreeing prefix.  The verify replays EXACTLY the
plain step's sampling (same per-lane rng chain, one split per emitted
token), so acceptance only keeps tokens the plain path would have
drawn: spec-on, spec-off and solo ``generate()`` stay bit-identical at
any temperature, and a faulted draft/verify path degrades to the plain
step chunk — token-identical, counted on
``pathway_serve_degraded_total{reason="speculation_disabled"}``.
Per-round cost stays inside the 2+2 dispatch budget: at most two
dispatches (draft + verify) and two host fetches (draft tokens +
emitted tokens).

**int8 KV pool** (``PATHWAY_DECODE_KV_QUANT=int8``): the slot pool is
stored int8 with per-(layer, head, channel) scales (ops/kv_quant.py),
dequantized inside the fused attention reads — slots×context per HBM
byte doubles, witnessed by the HBM ledger's ``kv_pool`` component and
the ``decode_slots`` exhaustion ETA.

Admission reuses the coalescing machinery from ``scheduler.py``
(``_CoalescerBase``): queue + tickets + deadline-preemption (a request
too tight for any queueing serves SOLO through the legacy path on its
caller's thread) + stop-drain.  Faults (``generator.prefill`` /
``generator.step`` / ``generator.slot_free`` chaos sites) degrade the
AFFECTED request — to an empty flagged result the QA layer's
``extractive_answer`` rung absorbs, or to its tokens emitted so far,
flagged — and never stall the step loop or touch another slot's K/V.

The decode loop's per-chunk dispatch+fetch is intentional (token-level
scheduling IS a host round trip per chunk — amortized over every
active slot), so this module is not marked serve-path for the
hidden-sync budget rules; lock discipline still applies and the pool
lock covers ONLY slot allocation, never a dispatch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import config, observe
from ..observe import hbm, trace
from ..robust import (
    Deadline,
    EXTRACTIVE_ANSWER,
    inject,
    log_once,
    record_degraded,
    retry_call,
)
from .scheduler import _Batch, _CoalescerBase, _Ticket

__all__ = ["ContinuousDecoder", "DecodeResult", "decode_slots"]


def decode_slots() -> int:
    """Slot-pool size from ``decode.slots`` (default 8): the max
    number of requests decoding concurrently in one step dispatch.
    More slots = more sharing per chunk but a larger resident pool
    (``slots × n_layers × max_len × d_model`` K/V elements × 2)."""
    return config.get("decode.slots")


# queue wait (enqueue → slot join) + per-phase device round trips
_H_QUEUE_WAIT = observe.histogram("pathway_generator_queue_wait_seconds")
_H_PREFILL = observe.histogram("pathway_generator_phase_seconds", phase="prefill")
_H_STEP = observe.histogram("pathway_generator_phase_seconds", phase="step")
# time-to-last-token per request, admission → completion at the waiter —
# the series the SLO engine's decode_ttlt objective reads
_H_TTLT = observe.histogram("pathway_generator_ttlt_seconds")
# accepted tokens per speculative round, PER LANE — token-valued on the
# seconds axis (observe_s(count): 1 token → the (0.5,1]s bucket, 2 →
# (1,2], 3-4 → (2,4], ...), so the power-of-two buckets resolve small
# counts exactly and _sum/_count recover the true mean acceptance
_H_DRAFT_ACCEPT = observe.histogram(
    "pathway_generator_draft_accepted_tokens"
)


class DecodeResult(str):
    """One request's generated text plus ladder metadata — a ``str``
    subclass so every existing caller that treats generator output as a
    string keeps working; ``.degraded`` / ``.meta`` follow the
    ``ServeResult`` convention (tuple of rung flags, JSON-able extras)."""

    def __new__(
        cls,
        text: str = "",
        degraded: Sequence[str] = (),
        meta: Optional[Dict[str, Any]] = None,
    ):
        self = super().__new__(cls, text)
        deduped: List[str] = []
        for flag in degraded:
            if flag not in deduped:
                deduped.append(flag)
        self.degraded = tuple(deduped)
        self.meta = dict(meta or {})
        if self.degraded and "degraded_reasons" not in self.meta:
            self.meta["degraded_reasons"] = list(self.degraded)
        return self

    @property
    def ok(self) -> bool:
        return not self.degraded


class _SlotState:
    """Host bookkeeping for one occupied slot (the authoritative K/V
    and rng state live device-side in the pool arrays)."""

    __slots__ = (
        "req", "budget", "temperature", "seed", "eos", "tokens", "pos",
        "left", "t_join_ns", "prompt_ids",
    )

    def __init__(self, req, budget: int, temperature: float, seed: int, eos: int):
        self.req = req
        self.budget = budget
        self.temperature = temperature
        self.seed = seed
        self.eos = eos
        self.tokens: List[int] = []
        self.pos = 0     # next K/V write position (= current length)
        self.left = 0    # decode-step tokens still allowed
        self.t_join_ns = time.perf_counter_ns()
        # prompt token ids (host copy) — the n-gram draft mining corpus
        self.prompt_ids: List[int] = []


def _spent_deadline() -> Deadline:
    """An already-expired deadline: armed ``hang`` faults on bookkeeping
    sites release immediately instead of wedging the step loop (the
    same contract the tracing layer uses for its chaos sites)."""
    return Deadline(0.0)


class ContinuousDecoder(_CoalescerBase):
    """Continuous-batching decode engine over one ``TextGenerator``.

    ``submit(prompt, max_new_tokens=, temperature=, seed=, deadline=)``
    returns a ticket resolving to a :class:`DecodeResult` whose tokens
    are identical to ``generator.generate([prompt], ...)`` solo at the
    same seed.  ``generate(prompts, ...)`` is the blocking batch
    convenience.  One scheduler thread owns the pool: it joins queued
    requests into free slots (prefill), advances every active slot in
    compiled step chunks, and resolves tickets as requests leave at
    EOS/budget — slots free mid-flight, so a stream of short requests
    rides alongside one long request instead of queueing behind it.
    """

    _degrade_empty = False
    _metric_prefix = "pathway_generator_queue"

    def __init__(
        self,
        generator,
        slots: Optional[int] = None,
        step_bucket: Optional[int] = None,
        name: Optional[str] = None,
        window_us: Optional[float] = None,
        autostart: bool = True,
        eos_id: Any = "inherit",
        kv_width: Optional[int] = None,
        spec_k: Optional[int] = None,
        draft: Optional[str] = None,
        kv_quant: Optional[str] = None,
    ):
        import jax.numpy as jnp

        from ..models.generator import (
            decode_draft_layers,
            decode_draft_source,
            decode_kv_quant,
            decode_spec_k,
            decode_step_bucket,
        )

        self.generator = generator
        cfg = generator.config
        self.slots = max(1, int(slots or decode_slots()))
        self.chunk = max(1, int(step_bucket or decode_step_bucket()))
        self.eos_id = generator.eos_id if eos_id == "inherit" else eos_id
        # speculative decode + KV-quant knobs — constructor args win,
        # env (PATHWAY_DECODE_SPEC_K / _DRAFT / _KV_QUANT) is the default
        self.spec_k = (
            decode_spec_k() if spec_k is None
            else max(0, min(int(spec_k), 16))
        )
        self.draft_source = (
            decode_draft_source() if draft is None
            else (draft if draft in ("auto", "ngram", "trunk") else "auto")
        )
        self.kv_quant = (
            decode_kv_quant() if kv_quant is None
            else ("int8" if kv_quant == "int8" else "bf16")
        )
        self._quant = self.kv_quant == "int8"
        self._draft_layers = decode_draft_layers(cfg.n_layers)
        # cooldown: after a draft/verify fault degrades a round to the
        # plain step, skip speculation for this many rounds so a
        # persistent fault doesn't pay the retry ladder on every chunk
        self._spec_hold = 0
        self._draft_sources = {"ngram": 0, "trunk": 0, "none": 0}
        # cross-request suffix corpus (the "prefix-cache blocks" half of
        # the n-gram well): every cleanly finished request feeds its full
        # token stream (prompt + emitted) into an n-gram → continuation
        # index, so a repeated or near-duplicate request drafts its whole
        # continuation from the previous run's output.  Greedy repeats
        # verify-accept wholesale; per-request sampling seeds reject
        # safely.  Engine-loop-thread only — no lock.
        self._suffix_idx: Dict[Tuple[int, ...], List[int]] = {}
        # pool buffer width: defaults to the position-embedding bound —
        # any prompt + budget the generator accepts fits (prompts are
        # tokenized to max_len - max_new_tokens), and masked attention
        # makes the width numerically invisible.  ``kv_width`` (or
        # ``PATHWAY_DECODE_KV_WIDTH``) narrows the pool when the served
        # workload is known-short: attention cost and per-step buffer
        # traffic scale with the width, and a request that does not fit
        # (prompt + budget > width) simply serves solo
        if kv_width is None:
            kv_width = config.get("decode.kv_width")
        self._T = min(cfg.max_len, kv_width) if kv_width else cfg.max_len
        H = cfg.n_heads
        hd = cfg.d_model // H
        if self._quant:
            # int8 pool + per-(layer, head, channel) stored scales — the
            # scales are derived from the generator's params off the
            # engine locks (generator.kv_pool_scales memoizes them)
            self._kscale, self._vscale = generator.kv_pool_scales()
            pool_dtype = jnp.int8
        else:
            self._kscale = self._vscale = None
            pool_dtype = cfg.dtype
        self._pk = jnp.zeros(
            (self.slots, cfg.n_layers, self._T, H, hd), pool_dtype
        )
        self._pv = jnp.zeros_like(self._pk)
        self._rngs = jnp.zeros((self.slots, 2), jnp.uint32)
        # slot allocation/free under the pool lock; dispatches NEVER
        # hold it (the analyzer's slot-pool lock convention)
        self._pool_lock = threading.Lock()
        self._free: List[int] = list(range(self.slots))
        self._active: Dict[int, _SlotState] = {}
        self.pool_stats: Dict[str, int] = {
            "tokens_prefill": 0,   # prompt tokens the prefill computed
            "tokens_decode": 0,    # tokens emitted (prefill sample + steps)
            "finished": 0,         # requests that left at EOS/budget
            "evicted": 0,          # requests resolved degraded (fault/deadline)
            "quarantined": 0,      # slots retired by slot_free faults
            "chunks": 0,           # step-chunk dispatches
            "steps": 0,            # decode steps executed (chunks × chunk)
            "occupancy_sum": 0,    # Σ active slots per chunk (avg = /chunks)
            "spec_rounds": 0,      # speculative draft→verify rounds
            "spec_fallbacks": 0,   # rounds degraded to the plain step
            "draft_offered": 0,    # draft tokens proposed (Σ lanes × k-1)
            "draft_accepted": 0,   # draft tokens accepted by the verify
        }
        super().__init__(
            name=name or f"decode-{observe.next_id()}",
            window_us=window_us,
            max_batch=self.slots,
            autostart=autostart,
        )
        # HBM ledger (observe/hbm.py): the slot KV pool is the
        # generator-side HBM owner; slot exhaustion-ETA derives from the
        # observed join rate vs frees at sample time
        hbm.track("decode", self, lambda d: d.hbm_components())
        hbm.track_resource(
            "decode_slots",
            self,
            lambda d: d.slots - len(d._free),
            lambda d: d.slots,
        )

    def hbm_bytes(self) -> int:
        """Device bytes of the persistent slot pool (K + V buffers +
        per-slot rng chains) — ``.nbytes`` metadata, never a sync."""
        return sum(
            int(getattr(buf, "nbytes", 0))
            for buf in (self._pk, self._pv, self._rngs)
        )

    def hbm_components(self) -> Dict[str, int]:
        """HBM-ledger components: the pool itself plus, under int8, the
        stored dequant scales — so the ledger shows the quantized pool's
        true footprint (int8 pool bytes + the tiny f32 scale arrays)
        next to the bf16 baseline's."""
        comp = {"kv_pool": self.hbm_bytes()}
        if self._quant:
            comp["kv_scales"] = sum(
                int(getattr(s, "nbytes", 0))
                for s in (self._kscale, self._vscale)
            )
        return comp

    # -- public surface ------------------------------------------------------
    def submit(
        self,
        prompt: str,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        deadline: Optional[Deadline] = None,
        eos_id: Any = "inherit",
    ) -> _Ticket:
        if deadline is None:
            deadline = Deadline.from_env()
        eos = self.eos_id if eos_id == "inherit" else eos_id
        ctx = trace.start_trace("generate.request", deadline=deadline)
        item = (
            str(prompt),
            int(max_new_tokens),
            float(temperature),
            int(seed),
            -1 if eos is None else int(eos),
        )
        return self._admit([item], None, deadline, trace_ctx=ctx)

    def generate(
        self,
        prompts: Sequence[str],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        deadline: Optional[Deadline] = None,
    ) -> List[str]:
        tickets = [
            self.submit(
                p, max_new_tokens, temperature, seed, deadline=deadline
            )
            for p in prompts
        ]
        return [t() for t in tickets]

    __call__ = generate

    # -- scheduler thread: the continuous step loop --------------------------
    def _run(self) -> None:
        while True:
            reqs: Optional[List[Any]] = None
            try:
                reqs = self._collect_joins()
                if reqs is None:
                    return
                if reqs:
                    self._join_group(reqs)
                if self._active:
                    if self._spec_ready():
                        self._spec_round()
                    else:
                        self._step_chunk()
            except Exception as exc:  # pragma: no cover - defensive
                # the loop must outlive any one bad iteration: resolve
                # every in-flight request with what it has, and any
                # popped-but-not-joined request with the error — every
                # admitted ticket resolves, no waiter hangs
                log_once(
                    f"decode.run:{type(exc).__name__}",
                    "continuous decode iteration failed (%r); degrading "
                    "in-flight requests and continuing",
                    exc,
                )
                self._evict_all(exc)
                for r in reqs or []:
                    if not r.event.is_set():
                        self._resolve_with_error(r, exc)

    def _collect_joins(self) -> Optional[List[Any]]:
        """Pop queued requests up to the free-slot count.  Blocks only
        when the pool is idle; with active slots it returns immediately
        so the step loop keeps advancing.  Returns None when stopped
        AND fully drained (queue empty, pool empty)."""
        with self._cond:
            if not self._active:
                while self._running and not self._queue:
                    self._cond.wait(0.1)
            if not self._queue and not self._active and not self._running:
                return None
            free = len(self._free)
            # every slot quarantined and nothing in flight: fall back to
            # per-request solo dispatches so admitted tickets still
            # resolve (the engine degrades to call-level batching)
            limit = free if (free or self._active) else len(self._queue)
            take: List[Any] = []
            while self._queue and len(take) < limit:
                r = self._queue.popleft()
                self._queued_items -= len(r.items)
                take.append(r)
            return take

    # -- join ---------------------------------------------------------------
    def _join_group(self, reqs: List[Any]) -> None:
        """Admit a cohort of queued requests: host prep (tokenize +
        prefix-cache walk) per request, then requests whose prefill
        shares a compile shape (suffix length, prefix split) batch into
        ONE prefill dispatch — the bucketed-join analog of the serve
        scheduler's coalesced stage-1 batches."""
        gen = self.generator
        cfg = gen.config
        ready: List[dict] = []
        for req in reqs:
            text, steps, temp, seed, eos = req.items[0]
            _H_QUEUE_WAIT.observe_ns(
                time.perf_counter_ns() - req.t_enqueue_ns
            )
            if req.deadline is not None and req.deadline.expired():
                self.pool_stats["evicted"] += 1
                record_degraded(EXTRACTIVE_ANSWER)
                self._resolve(
                    req,
                    DecodeResult(
                        "", degraded=(EXTRACTIVE_ANSWER,),
                        meta={"reason": "deadline_before_join"},
                    ),
                )
                continue
            try:
                # host prep — tokenize + prefix-cache walk — off every
                # lock.  Per-request guard: a bad request (e.g. a budget
                # larger than the model's max_len) must resolve ITS
                # ticket degraded, never hang the cohort's
                L_budget = cfg.max_len - steps
                if L_budget <= 0:
                    raise ValueError(
                        f"max_new_tokens={steps} leaves no prompt budget "
                        f"(max_len={cfg.max_len})"
                    )
                ids, mask = gen.tokenizer.encode_batch(
                    [text], max_length=L_budget
                )
                ids = np.asarray(ids)
                n = int(np.asarray(mask).sum())
                if ids.shape[1] + steps > self._T:
                    # narrowed pool (kv_width): this request does not fit
                    # — serve it solo through the legacy path instead
                    self._dispatch_batch([req], solo=True)
                    continue
                P, matches = 0, []
                if gen.kv_cache is not None:
                    P, matches = gen._cached_prefix(
                        ids, np.asarray([n], np.int32), 1
                    )
            except Exception as exc:
                log_once(
                    f"decode.join:{type(exc).__name__}",
                    "continuous-decode join prep failed (%r); degrading "
                    "the request to an empty flagged result",
                    exc,
                )
                self.pool_stats["evicted"] += 1
                record_degraded(EXTRACTIVE_ANSWER)
                self._resolve(
                    req,
                    DecodeResult(
                        "", degraded=(EXTRACTIVE_ANSWER,),
                        meta={"error": repr(exc)},
                    ),
                )
                continue
            ready.append(dict(
                req=req, ids=ids, n=n, P=P,
                match=matches[0] if matches else None,
                L_sfx=ids.shape[1] - P, steps=steps, temp=temp,
                seed=seed, eos=eos,
            ))
        with self._pool_lock:
            free = len(self._free)
        if len(ready) > free:
            # more admitted than free slots (quarantine exhaustion):
            # the overflow serves solo so every ticket still resolves
            for rec in ready[free:]:
                self._dispatch_batch([rec["req"]], solo=True)
            ready = ready[:free]
        # cohort grouping: one batched prefill per PREFIX split; members
        # with shorter suffixes are right-padded to the group width (pad
        # positions carry garbage K/V that the decode overwrites before
        # it could ever be attended — causal masking + write-before-read)
        groups: Dict[int, List[dict]] = {}
        for rec in ready:
            groups.setdefault(rec["P"], []).append(rec)
        for P, grp in groups.items():
            # quantize the cohort suffix width UP to a power-of-two ×16
            # bucket (capped at the pool width) so the prefill shape
            # lattice stays O(log²) — compile churn, not correctness,
            # is the enemy here: pad positions are write-before-read
            L = max(r["L_sfx"] for r in grp)
            L_pad = 16
            while L_pad < L:
                L_pad *= 2
            self._prefill_group(grp, min(L_pad, self._T - P), P)

    def _prefill_group(self, grp: List[dict], L_sfx: int, P: int) -> None:
        import jax
        import jax.numpy as jnp

        gen = self.generator
        cfg = gen.config
        H = cfg.n_heads
        hd = cfg.d_model // H
        n_real = len(grp)
        with self._pool_lock:
            slots_real = [self._free.pop() for _ in range(n_real)]
        # batch bucket: the model batch buckets (1, 4, 16, ...), so a
        # burst of joins costs O(log) compile signatures per cohort size
        B = 1
        while B < n_real:
            B *= 4
        pad = B - n_real
        try:
            # real rows first; pad rows scatter to the out-of-bounds
            # index ``slots`` (dropped by the scatter, never a clobber)
            slot_arr = np.asarray(
                slots_real + [self.slots] * pad, np.int32
            )
            suffix = np.zeros((B, L_sfx), np.int32)
            n_len = np.zeros(B, np.int32)
            temps = np.zeros(B, np.float32)
            rng_rows: List[Any] = []
            for j, rec in enumerate(grp):
                row = rec["ids"][0, P:]
                suffix[j, : row.shape[0]] = row
                n_len[j] = rec["n"]
                temps[j] = rec["temp"]
                rng_rows.append(np.asarray(jax.random.PRNGKey(rec["seed"])))
            rng_rows += [np.zeros(2, np.uint32)] * pad
            if P:
                blk = gen.kv_cache.block
                zero = np.zeros((cfg.n_layers, P, H, hd), np.float32)
                rows_k: List[Any] = []
                rows_v: List[Any] = []
                for rec in grp:
                    blocks = rec["match"][1][: P // blk]
                    rows_k.append(
                        jnp.concatenate([b[0] for b in blocks], axis=1)
                    )
                    rows_v.append(
                        jnp.concatenate([b[1] for b in blocks], axis=1)
                    )
                rows_k += [zero] * pad
                rows_v += [zero] * pad
                prefix_k = jnp.stack(
                    [jnp.asarray(r, cfg.dtype) for r in rows_k]
                )
                prefix_v = jnp.stack(
                    [jnp.asarray(r, cfg.dtype) for r in rows_v]
                )
            else:
                prefix_k = jnp.zeros((B, cfg.n_layers, 0, H, hd), cfg.dtype)
                prefix_v = jnp.zeros((B, cfg.n_layers, 0, H, hd), cfg.dtype)
            with gen._lock:
                fn = gen._slot_prefill_fn(
                    self.slots, self._T, B, L_sfx, P, self._quant
                )
            sc = (self._kscale, self._vscale) if self._quant else ()
            deadline = self._batch_deadline([rec["req"] for rec in grp])
            t0 = time.perf_counter_ns()
            # pathway: allow(recompile-hazard): prefill shapes are bucketed upstream — the tokenizer pads suffix length to /16 multiples, the prefix split is a power-of-two block multiple (PrefixKVCache.bucket_tokens) and the join batch is a power-of-two bucket; the census test bounds the signature set
            pk, pv, toks, rngs_out = retry_call(
                "generator.prefill",
                fn,
                gen.params,
                self._pk,
                self._pv,
                jnp.asarray(slot_arr),
                jnp.asarray(suffix),
                jnp.asarray(n_len),
                prefix_k,
                prefix_v,
                jnp.asarray(np.stack(rng_rows)),
                jnp.asarray(temps),
                *sc,
                deadline=deadline,
            )
            firsts = np.asarray(toks)  # pathway: allow(value-flow): the prefill JOIN's one deliberate host fetch — first tokens must reach the riders' tickets before the step loop takes over
            t1 = time.perf_counter_ns()
            _H_PREFILL.observe_ns(t1 - t0)
        except Exception as exc:
            for slot in slots_real:
                self._free_slot(slot)
            log_once(
                f"decode.prefill:{type(exc).__name__}",
                "continuous-decode prefill failed (%r); degrading the "
                "affected request(s) to empty flagged results",
                exc,
            )
            for rec in grp:
                self.pool_stats["evicted"] += 1
                record_degraded(EXTRACTIVE_ANSWER)
                self._resolve(
                    rec["req"],
                    DecodeResult(
                        "", degraded=(EXTRACTIVE_ANSWER,),
                        meta={"error": repr(exc)},
                    ),
                )
            return
        self._pk, self._pv = pk, pv
        self._rngs = self._rngs.at[jnp.asarray(slots_real)].set(
            rngs_out[:n_real]
        )
        pk_now, pv_now = self._pk, self._pv
        for j, rec in enumerate(grp):
            req = rec["req"]
            slot = slots_real[j]
            first = int(firsts[j])
            # prefix capture: admit the prompt's uncached full blocks as
            # async device slices of THIS pool version (functional
            # arrays — later steps never mutate them)
            if gen.kv_cache is not None:
                blk = gen.kv_cache.block
                matched, _blocks, chain = rec["match"]
                if self._quant:
                    # int8 pool: captured blocks dequantize back to the
                    # cache's bf16 convention; a warm join re-quantizes
                    # them — idempotent (ops/kv_quant.py), so warm pool
                    # bytes match cold ones bit-for-bit
                    from ..ops.kv_quant import dequantize_kv

                    def capture(jb, _s=slot):
                        return (
                            dequantize_kv(
                                pk_now[_s, :, jb * blk : (jb + 1) * blk],
                                self._kscale, cfg.dtype,
                            ),
                            dequantize_kv(
                                pv_now[_s, :, jb * blk : (jb + 1) * blk],
                                self._vscale, cfg.dtype,
                            ),
                        )
                else:
                    def capture(jb, _s=slot):
                        return (
                            pk_now[_s, :, jb * blk : (jb + 1) * blk],
                            pv_now[_s, :, jb * blk : (jb + 1) * blk],
                        )
                gen.kv_cache.admit(chain, matched // blk, capture)
                gen.kv_cache.note_prefill(reused=P, computed=rec["n"] - P)
            self.pool_stats["tokens_prefill"] += rec["n"] - P
            self.pool_stats["tokens_decode"] += 1
            if req.trace is not None:
                req.trace.add_span(
                    "decode.prefill", t0, t1,
                    slot=slot, prefix_tokens=P, suffix_tokens=L_sfx,
                    join_batch=n_real,
                )
            state = _SlotState(
                req, rec["steps"], rec["temp"], rec["seed"], rec["eos"]
            )
            state.tokens = [first]
            state.pos = rec["n"]
            state.left = rec["steps"] - 1
            # host copy of the prompt ids: the draft miner's corpus
            state.prompt_ids = [int(t) for t in rec["ids"][0, : rec["n"]]]
            self._active[slot] = state
            if (rec["eos"] >= 0 and first == rec["eos"]) or state.left <= 0:
                self._leave(slot, state)

    # -- decode step chunk ---------------------------------------------------
    def _step_chunk(self) -> None:
        import jax.numpy as jnp

        gen = self.generator
        S = self.slots
        tok = np.zeros(S, np.int32)
        pos = np.zeros(S, np.int32)
        act = np.zeros(S, bool)
        left = np.zeros(S, np.int32)
        temps = np.zeros(S, np.float32)
        eos = np.full(S, -1, np.int32)
        for s, st in self._active.items():
            tok[s] = st.tokens[-1]
            pos[s] = st.pos
            act[s] = True
            left[s] = st.left
            temps[s] = st.temperature
            eos[s] = st.eos
        with gen._lock:
            fn = gen._slot_step_fn(S, self._T, self.chunk, self._quant)
        sc = (self._kscale, self._vscale) if self._quant else ()
        deadline = self._batch_deadline(
            [st.req for st in self._active.values()]
        )
        riders = [
            st for st in self._active.values() if st.req.trace is not None
        ]
        bctx = None
        if riders:
            # ONE batch trace per step chunk, linked from every traced
            # rider — the decode-loop analog of the coalescing
            # scheduler's batch/link-span pattern
            bctx = trace.start_trace(
                "decode.batch", deadline=deadline, kind="batch", sample=False
            )
            if bctx is not None:
                bctx.annotate(
                    engine=self.name, slots=len(self._active),
                    chunk=self.chunk,
                )
        t0 = time.perf_counter_ns()
        try:
            args = (
                gen.params, self._pk, self._pv, jnp.asarray(tok),
                jnp.asarray(pos), jnp.asarray(act), jnp.asarray(left),
                self._rngs, jnp.asarray(temps), jnp.asarray(eos), *sc,
            )
            if bctx is not None:
                with trace.use(bctx):
                    pk, pv, rngs, em = retry_call(
                        "generator.step", fn, *args, deadline=deadline
                    )
            else:
                pk, pv, rngs, em = retry_call(
                    "generator.step", fn, *args, deadline=deadline
                )
            em = np.asarray(em)  # [chunk, S]: the per-chunk host fetch  # pathway: allow(value-flow): THE decode-loop fetch — one deliberate sync per step chunk delivers every slot's tokens to its rider
        except Exception as exc:
            if bctx is not None:
                trace.finish(bctx, statuses=("error",))
            log_once(
                f"decode.step:{type(exc).__name__}",
                "continuous-decode step chunk failed (%r); resolving "
                "in-flight requests with their tokens so far",
                exc,
            )
            self._evict_all(exc)
            return
        t1 = time.perf_counter_ns()
        _H_STEP.observe_ns(t1 - t0)
        self._pk, self._pv, self._rngs = pk, pv, rngs
        self.pool_stats["chunks"] += 1
        self.pool_stats["steps"] += self.chunk
        self.pool_stats["occupancy_sum"] += len(self._active)
        if bctx is not None:
            trace.finish(bctx)
            for st in riders:
                rt = st.req.trace
                rt.add_link(bctx.trace_id)
                rt.add_span(
                    "decode.step", t0, t1,
                    linked_trace=bctx.trace_id, slots=len(self._active),
                )
        # replay the chunk per slot — the EXACT mask rules the kernel
        # applied: a lane emits until EOS or budget, then freezes
        leaves: List[Tuple[int, _SlotState, Tuple[str, ...]]] = []
        for s, st in list(self._active.items()):
            flags: Tuple[str, ...] = ()
            finished = False
            for i in range(self.chunk):
                t = int(em[i, s])  # pathway: allow(value-flow): `em` was rebound to its HOST copy at the fetch above — the rule's name-level residency tracking cannot see the rebind; no device touch happens here
                st.tokens.append(t)
                st.pos += 1
                st.left -= 1
                self.pool_stats["tokens_decode"] += 1
                if (st.eos >= 0 and t == st.eos) or st.left <= 0:
                    finished = True
                    break
            if not finished and (
                st.req.deadline is not None and st.req.deadline.expired()
            ):
                # mid-decode deadline: the request leaves with its
                # tokens so far, flagged — its slot frees for the queue
                finished = True
                flags = (EXTRACTIVE_ANSWER,)
            if finished:
                leaves.append((s, st, flags))
        for s, st, flags in leaves:
            self._leave(s, st, flags=flags)

    # -- speculative decode: draft → verify → accept -------------------------
    def _spec_ready(self) -> bool:
        """Should this iteration run a speculative round?  Requires
        ``spec_k >= 2`` (one committed token + at least one draft), no
        active fault cooldown, and room for all ``k`` K/V writes in
        every active lane (``dynamic_update_slice`` CLAMPS out-of-bounds
        starts, so a lane with pos+k > T would silently clobber valid
        rows — near the width frontier the engine takes plain steps)."""
        k = self.spec_k
        if k < 2:
            return False
        if self._spec_hold > 0:
            self._spec_hold -= 1
            return False
        return all(
            st.pos + k <= self._T for st in self._active.values()
        )

    @staticmethod
    def _mine_ngram(hist: List[int], want: int) -> List[int]:
        """Prompt-lookup draft mining: find the RIGHTMOST earlier
        occurrence of the history's trailing n-gram (n = 3, then 2,
        then 1) and propose the tokens that followed it.  RAG prompts
        quote their evidence, so generations re-walk prompt n-grams
        constantly — free drafts, no dispatch.  Host-side over a few
        hundred ints; returns [] when the well is dry."""
        L = len(hist)
        for n in (3, 2, 1):
            if L < n + 1:
                continue
            pat = hist[-n:]
            for j in range(L - n - 1, -1, -1):
                if hist[j : j + n] == pat:
                    cont = hist[j + n : j + n + want]
                    if cont:
                        return cont
        return []

    def _remember(self, st: _SlotState) -> None:
        """Feed a finished request's token stream into the suffix
        index.  Every n-gram (n = 1..6) of the stream maps
        to the (up to 16) tokens that followed it; the most recent
        writer wins, so the index tracks live traffic.  O(len) dict
        writes per finished request, bounded by a clear-on-overflow."""
        seq = st.prompt_ids + st.tokens
        if len(seq) < 2:
            return
        idx = self._suffix_idx
        if len(idx) > 100_000:
            idx.clear()  # bounded memory: rebuilt by ongoing traffic
        # WITHIN a sequence the FIRST occurrence wins (a later
        # overlapping occurrence inside a repeated-token run would
        # otherwise skip the rest of the run); ACROSS sequences the
        # most recent request wins, tracking live traffic
        fresh: Dict[Tuple[int, ...], List[int]] = {}
        for n in range(1, 7):
            for i in range(len(seq) - n):
                fresh.setdefault(
                    tuple(seq[i : i + n]), seq[i + n : i + n + 16]
                )
        idx.update(fresh)

    def _mine_corpus(self, hist: List[int], want: int) -> List[int]:
        """Cross-request half of ``_mine_ngram``: look the history's
        trailing n-gram up in the suffix index, longest context first —
        near-duplicate requests (shared RAG prefixes) collide on short
        n-grams, and the deeper context disambiguates which stream to
        continue.  O(1) per lane per round."""
        for n in (6, 5, 4, 3, 2, 1):
            if len(hist) < n:
                continue
            cont = self._suffix_idx.get(tuple(hist[-n:]))
            if cont:
                return cont[:want]
        return []

    def _spec_round(self) -> None:
        """One draft→verify→accept round over the pool: propose ``k-1``
        tokens per lane (n-gram mining, trunk fallback), verify all
        ``k`` positions in ONE batched dispatch, commit each lane's
        longest agreeing prefix.  Tokens are EXACTLY the plain path's
        (the verify replays its sampling rng-for-rng); only the number
        of dispatches per token changes.  Any draft/verify fault falls
        back to the plain step chunk for this round — pool untouched,
        token-identical — and arms a cooldown."""
        import jax.numpy as jnp

        gen = self.generator
        S = self.slots
        k = self.spec_k
        toks = np.zeros((S, k), np.int32)
        pos = np.zeros(S, np.int32)
        act = np.zeros(S, bool)
        left = np.zeros(S, np.int32)
        temps = np.zeros(S, np.float32)
        eos = np.full(S, -1, np.int32)
        src_of: Dict[int, str] = {}
        need_trunk: List[int] = []
        for s, st in self._active.items():
            toks[s, 0] = st.tokens[-1]
            pos[s] = st.pos
            act[s] = True
            left[s] = st.left
            temps[s] = st.temperature
            eos[s] = st.eos
            mined: List[int] = []
            if self.draft_source in ("auto", "ngram"):
                hist = st.prompt_ids + st.tokens
                mined = self._mine_ngram(hist, k - 1)
                pooled = self._mine_corpus(hist, k - 1)
                if len(pooled) > len(mined):
                    mined = pooled
            if mined:
                toks[s, 1 : 1 + len(mined)] = mined
                src_of[s] = "ngram"
            elif self.draft_source in ("auto", "trunk"):
                need_trunk.append(s)
                src_of[s] = "trunk"
            else:
                src_of[s] = "none"
        sc = (self._kscale, self._vscale) if self._quant else ()
        with gen._lock:
            vfn = gen._slot_verify_fn(S, self._T, k, self._quant)
            dfn = gen._slot_draft_fn(
                S, self._T, k - 1, self._draft_layers, self._quant
            )
        deadline = self._batch_deadline(
            [st.req for st in self._active.values()]
        )
        riders = [
            st for st in self._active.values() if st.req.trace is not None
        ]
        bctx = None
        if riders:
            bctx = trace.start_trace(
                "decode.batch", deadline=deadline, kind="batch", sample=False
            )
            if bctx is not None:
                bctx.annotate(
                    engine=self.name, slots=len(self._active),
                    spec_k=k, spec=True,
                )
        t0 = time.perf_counter_ns()
        try:
            # draft phase: ONE reduced-trunk dispatch covers every lane
            # that needs it; pure-ngram rounds still fire the chaos site
            # so a faulted draft path degrades ALL speculation uniformly
            if need_trunk:
                # pathway: allow(recompile-hazard): every operand shape is static per engine — [S] / [S, k] with S = the pool size and k = spec_k, fixed at construction; the census test pins the signature count
                dr = retry_call(
                    "generator.draft",
                    dfn,
                    gen.params, self._pk, self._pv,
                    jnp.asarray(toks[:, 0]), jnp.asarray(pos),
                    jnp.asarray(act), *sc,
                    deadline=deadline,
                )
                dr = np.asarray(dr)  # pathway: allow(value-flow): the draft fetch — proposals are host state (they seed the verify's token operand), one deliberate sync per speculative round
                for s in need_trunk:
                    toks[s, 1:] = dr[s]
            else:
                inject.fire("generator.draft", deadline=deadline)
            # verify phase: ONE batched dispatch scores all k positions
            args = (
                gen.params, self._pk, self._pv, jnp.asarray(toks),
                jnp.asarray(pos), jnp.asarray(act), jnp.asarray(left),
                self._rngs, jnp.asarray(temps), jnp.asarray(eos), *sc,
            )
            if bctx is not None:
                with trace.use(bctx):
                    pk, pv, rngs, em = retry_call(
                        "generator.verify", vfn, *args, deadline=deadline
                    )
            else:
                pk, pv, rngs, em = retry_call(
                    "generator.verify", vfn, *args, deadline=deadline
                )
            em = np.asarray(em)  # [k, S]  # pathway: allow(value-flow): THE decode-loop fetch (speculative flavor) — one deliberate sync per round delivers every slot's accepted tokens to its rider
        except Exception as exc:
            if bctx is not None:
                trace.finish(bctx, statuses=("speculation_disabled",))
            # degrade-never-fail: the pool was NOT rebound (functional
            # updates — a failed dispatch leaves no partial state), so
            # the plain chunk below produces exactly the tokens the
            # spec round would have committed
            log_once(
                f"decode.spec:{type(exc).__name__}",
                "speculative round failed (%r); falling back to the "
                "plain step chunk (token-identical) and cooling down",
                exc,
            )
            self.pool_stats["spec_fallbacks"] += 1
            record_degraded("speculation_disabled")
            self._spec_hold = 8
            self._step_chunk()
            return
        t1 = time.perf_counter_ns()
        _H_STEP.observe_ns(t1 - t0)
        self._pk, self._pv, self._rngs = pk, pv, rngs
        self.pool_stats["chunks"] += 1
        self.pool_stats["steps"] += k
        self.pool_stats["spec_rounds"] += 1
        self.pool_stats["occupancy_sum"] += len(self._active)
        if bctx is not None:
            trace.finish(bctx)
            for st in riders:
                rt = st.req.trace
                rt.add_link(bctx.trace_id)
                rt.add_span(
                    "decode.step", t0, t1,
                    linked_trace=bctx.trace_id, slots=len(self._active),
                    spec_k=k,
                )
        # replay: commit each lane's accepted prefix — ``-1`` marks the
        # first rejected position (acceptance is a PREFIX by
        # construction); EOS inside the accepted prefix truncates it
        # there and frees the slot THIS round, exactly like a plain
        # chunk whose lane hits EOS mid-chunk
        leaves: List[Tuple[int, _SlotState, Tuple[str, ...]]] = []
        for s, st in list(self._active.items()):
            emitted = 0
            flags: Tuple[str, ...] = ()
            finished = False
            for i in range(k):
                t = int(em[i, s])  # pathway: allow(value-flow): `em` was rebound to its HOST copy at the fetch above — no device touch here
                if t < 0:
                    break
                st.tokens.append(t)
                st.pos += 1
                st.left -= 1
                emitted += 1
                self.pool_stats["tokens_decode"] += 1
                if (st.eos >= 0 and t == st.eos) or st.left <= 0:
                    finished = True
                    break
            _H_DRAFT_ACCEPT.observe_s(float(emitted))
            self.pool_stats["draft_offered"] += k - 1
            self.pool_stats["draft_accepted"] += max(0, emitted - 1)
            self._draft_sources[src_of.get(s, "none")] += 1
            if not finished and (
                st.req.deadline is not None and st.req.deadline.expired()
            ):
                finished = True
                flags = (EXTRACTIVE_ANSWER,)
            if finished:
                leaves.append((s, st, flags))
        for s, st, flags in leaves:
            self._leave(s, st, flags=flags)

    # -- leave / resolve -----------------------------------------------------
    def _leave(
        self, slot: int, st: _SlotState, flags: Tuple[str, ...] = ()
    ) -> None:
        gen = self.generator
        meta: Dict[str, Any] = {"tokens": len(st.tokens), "slot": slot}
        if flags:
            self.pool_stats["evicted"] += 1
            meta["partial"] = True
            for f in flags:
                record_degraded(f)
        else:
            self.pool_stats["finished"] += 1
            if self.spec_k >= 2:
                self._remember(st)
        if st.req.trace is not None:
            st.req.trace.add_span(
                "decode", st.t_join_ns, time.perf_counter_ns(),
                tokens=len(st.tokens), slot=slot,
            )
        # free BEFORE resolving: the waiter may act on the result the
        # instant the ticket fires, and the slot hand-off (including its
        # chaos site) must already be settled by then
        self._active.pop(slot, None)
        self._free_slot(slot)
        self._resolve(
            st.req,
            DecodeResult(
                gen.render_tokens(st.tokens), degraded=flags, meta=meta
            ),
        )

    def _evict_all(self, exc: BaseException) -> None:
        """Persistent step failure: every in-flight request resolves
        with its tokens emitted so far, flagged — the step loop itself
        survives and keeps serving the queue."""
        gen = self.generator
        for s, st in list(self._active.items()):
            self.pool_stats["evicted"] += 1
            record_degraded(EXTRACTIVE_ANSWER)
            self._active.pop(s, None)
            self._free_slot(s)
            self._resolve(
                st.req,
                DecodeResult(
                    gen.render_tokens(st.tokens),
                    degraded=(EXTRACTIVE_ANSWER,),
                    meta={
                        "partial": True,
                        "tokens": len(st.tokens),
                        "error": repr(exc),
                    },
                ),
            )

    def _free_slot(self, slot: int) -> None:
        """Return a slot to the free list.  A ``generator.slot_free``
        fault quarantines the slot (capacity shrinks by one, counted)
        instead of risking a corrupt hand-off — and fires under an
        already-spent deadline so even an armed hang releases
        immediately and the step loop never stalls."""
        try:
            inject.fire("generator.slot_free", deadline=_spent_deadline())
        except Exception as exc:
            log_once(
                f"decode.slot_free:{type(exc).__name__}",
                "slot free failed (%r); quarantining slot instead of "
                "reusing it",
                exc,
            )
            self.pool_stats["quarantined"] += 1
            return
        with self._pool_lock:
            self._free.append(slot)

    def _resolve(self, req, result: DecodeResult) -> None:
        req.slots = [0]
        req.batch = _Batch(
            lambda _r=result: [_r], 1, 1, self._degrade_empty
        )
        req.event.set()
        if req.trace is not None:
            trace.finish(req.trace, statuses=tuple(result.degraded))

    # -- solo fallback (deadline preemption, stop-drain, quarantine) ---------
    def _launch(self, items: List[Any], reqs: List[Any]):
        gen = self.generator

        def run(_items=tuple(items)):
            out = []
            for text, steps, temp, seed, eos in _items:
                rows = gen.generate(
                    [text],
                    max_new_tokens=steps,
                    temperature=temp,
                    seed=seed,
                    eos_id=None if eos < 0 else eos,
                )
                out.append(DecodeResult(rows[0]))
            return out

        return run

    def _demux(self, req, batch_result) -> DecodeResult:
        # time-to-last-token, pool and solo paths alike (the waiter's
        # completion is the client-visible "last token")
        _H_TTLT.observe_ns(time.perf_counter_ns() - req.t_enqueue_ns)
        out = []
        for slot in req.slots:
            if 0 <= slot < len(batch_result):
                out.append(batch_result[slot])
            else:  # pragma: no cover - defensive
                out.append(
                    DecodeResult("", degraded=(EXTRACTIVE_ANSWER,))
                )
        result = out[0]
        if req.trace is not None:
            # solo-path requests (deadline preemption, stop-drain,
            # quarantine/kv_width fallback) resolve through here without
            # passing _resolve — finish their trace so tail sampling
            # sees them (idempotent for pool-path requests)
            trace.finish(
                req.trace, statuses=tuple(getattr(result, "degraded", ()))
            )
        return result

    # -- flight-recorder provider -------------------------------------------
    def observe_metrics(self):
        yield from super().observe_metrics()
        labels = {"generator": self.name}
        yield ("gauge", "pathway_generator_slots", labels, self.slots)
        yield (
            "gauge", "pathway_generator_slots_active", labels,
            len(self._active),
        )
        yield (
            "gauge", "pathway_generator_slots_quarantined", labels,
            self.pool_stats["quarantined"],
        )
        for phase in ("prefill", "decode"):
            yield (
                "counter",
                "pathway_generator_tokens_total",
                {**labels, "phase": phase},
                self.pool_stats[f"tokens_{phase}"],
            )
        for outcome in ("finished", "evicted"):
            yield (
                "counter",
                "pathway_generator_requests_total",
                {**labels, "outcome": outcome},
                self.pool_stats[outcome],
            )
        yield (
            "counter", "pathway_generator_steps_total", labels,
            self.pool_stats["steps"],
        )
        yield (
            "counter", "pathway_generator_chunks_total", labels,
            self.pool_stats["chunks"],
        )
        # speculative decode: acceptance rate (accepted draft tokens /
        # offered draft tokens — 0.0 before any round) + which proposer
        # produced each lane-round's drafts.  All three sources render
        # even at zero so dashboards see the full label space
        offered = self.pool_stats["draft_offered"]
        yield (
            "gauge", "pathway_generator_draft_acceptance_rate", labels,
            (self.pool_stats["draft_accepted"] / offered) if offered else 0.0,
        )
        for source in ("ngram", "trunk", "none"):
            yield (
                "counter",
                "pathway_generator_draft_source_total",
                {**labels, "source": source},
                self._draft_sources[source],
            )
