"""Live-ingest runner + the freshness plane: ingest→retrievable, attributed.

The reference is an *incremental* dataflow engine — live data is its
identity — yet until round 19 the serve tier only read static indexes.
This module closes the gap: a continuous maintenance loop pulls
committed rows from connector sessions (the ``io/_connector.py`` idiom:
per-connector ``ConnectorMonitor`` + ``OffsetAntichain`` committed
positions), embeds them in bucketed off-serve-path batches, and absorbs
into the IVF **and** forward index under live serve traffic using their
existing off-lock-plan/locked-commit discipline.  Each document is
stamped at connector commit and becomes *retrievable* when the absorb
commit bumps the index generation — the scheduler's generation-keyed
result cache makes new documents visible to the next serve without any
invalidation traffic.

The freshness plane attributes every stage of that journey:

- ``pathway_freshness_seconds`` — arrival → retrievable, per document;
  ``pathway_freshness_stage_seconds{stage=queue_wait|embed|absorb_plan|
  commit}`` breaks the journey down (queue-wait per document; the three
  batch stages once per batch).
- one ingest trace per absorb batch (``kind="ingest"``) riding the
  round-13 TraceContext machinery, rooted at the OLDEST rider's arrival
  so the root duration IS that document's freshness; per-stage spans
  with explicit timestamps sum exactly to it.  A slow batch keeps its
  trace like a slow serve does (trace.py's tail sampler reads this
  module's histogram), and a batch older than the freshness SLO
  threshold is force-kept.
- maintenance-lag gauges per runner (docs pending, oldest-pending age,
  per-connector lag from ``ConnectorMonitor``) via the recorder's
  provider mechanism — zero hot-path cost, sampled at scrape time, and
  surfaced as the ``ingest`` column on ``/serve_stats``.
- the ``freshness`` SLO (observe/slo.py) reads the histogram AND
  ``overdue_pending()`` — queue residents older than the threshold burn
  budget *now*, so shedding starts while the backlog ages rather than
  after it lands.

Control loop closure: when ``serve_latency`` is firing and ``freshness``
is not, serve p99 is the binding constraint — the loop yields its absorb
cadence (``PATHWAY_INGEST_BACKPRESSURE_MS``, counted on
``pathway_ingest_backpressure_total``).  The reverse direction lives in
the scheduler: freshness burn feeds ``should_shed()`` which sheds
shed-class priorities at admission.

Degrade-never-fail chaos sites, all fired under a spent deadline so an
armed hang releases instantly:

- ``ingest.poll`` — the dequeue; a fault RETRIES (documents stay
  queued, nothing lost);
- ``ingest.embed`` — the encoder dispatch; a fault DROPS the batch's
  documents (counted on ``pathway_ingest_failures_total{stage}``);
- ``ingest.commit`` — the index commit; a fault DROPS the batch.

A faulted stage affects only its own documents: serve results stay
clean and bit-identical (the index simply does not advance), which is
exactly what tests/test_robust.py's ingest triples assert.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .. import config, observe
from ..observe import slo as slo_mod
from ..observe import trace
from ..robust import Deadline, inject, log_once

__all__ = ["IngestConnector", "LiveIngestRunner", "ingest_runners"]

_STAGES = ("queue_wait", "embed", "absorb_plan", "commit")

# pre-created at import so the families render at 0 on /metrics before
# the first fault/document (metrics-inventory drift gate convention)
_H_FRESH = observe.histogram("pathway_freshness_seconds")
_H_STAGE = {
    s: observe.histogram("pathway_freshness_stage_seconds", stage=s)
    for s in _STAGES
}
_C_FAIL = {
    s: observe.counter("pathway_ingest_failures_total", stage=s)
    for s in ("poll", "embed", "commit")
}

_runners: "weakref.WeakSet" = weakref.WeakSet()


def ingest_runners() -> List["LiveIngestRunner"]:
    """Live runners (weak registry) — read by the freshness SLO's
    overdue-pending term and by tests."""
    return list(_runners)


def _spent() -> Deadline:
    return Deadline.after_ms(0.0)


def _stage_allowed(site: str) -> bool:
    """Chaos gate, trace-path style: True = proceed normally.  ANY armed
    fault at ``site`` (raise, delay, hang) counts as a stage fault; the
    spent deadline means an armed hang releases immediately and a delay
    is clamped to ~10 ms — maintenance must never stall unboundedly."""
    if not inject.any_armed():
        return True
    try:
        before = inject.fired_count(site)
        inject.fire(site, deadline=_spent())
        return inject.fired_count(site) == before
    except Exception:
        return False


class _Doc:
    __slots__ = ("key", "text", "t_arrival_ns", "connector")

    def __init__(self, key: int, text: str, t_arrival_ns: int, connector: str):
        self.key = int(key)
        self.text = str(text)
        self.t_arrival_ns = int(t_arrival_ns)
        self.connector = connector


class IngestConnector:
    """The live twin of ``io/_connector.py``'s ``SessionWriter``: buffers
    keyed rows, stamps them at ``commit()`` (the arrival clock the
    freshness plane attributes from), folds committed per-partition
    offsets into its ``ConnectorMonitor`` antichain, and hands the batch
    to its runner's pending queue.  Offsets follow the SessionWriter
    contract exactly — ``commit()`` returns the merged antichain like
    ``SessionWriter.commit_offsets`` does."""

    def __init__(self, runner: "LiveIngestRunner", name: str):
        # lazy, like SessionWriter.__init__: keeps the serve import
        # graph free of the io connector zoo until a connector exists
        from ..io._offsets import ConnectorMonitor

        self._runner = runner
        self.name = str(name)
        self.monitor = ConnectorMonitor(self.name)
        self._buf: List[Tuple[int, str]] = []
        self._lock = threading.Lock()

    def insert(self, key: int, text: str) -> None:
        with self._lock:
            self._buf.append((int(key), str(text)))
        self.monitor.on_insert()

    def insert_rows(self, rows: Iterable[Tuple[int, str]]) -> None:
        rows = [(int(k), str(t)) for k, t in rows]
        with self._lock:
            self._buf.extend(rows)
        self.monitor.on_insert(len(rows))

    def commit(self, offsets: Optional[Mapping[Any, Any]] = None):
        """Commit buffered rows: each document's freshness clock starts
        HERE (connector commit), mirroring the reference's
        commit-at-autocommit-tick semantics."""
        from ..io._offsets import OffsetAntichain

        with self._lock:
            rows, self._buf = self._buf, []
        t = time.perf_counter_ns()
        docs = [_Doc(k, txt, t, self.name) for k, txt in rows]
        self.monitor.on_commit(
            OffsetAntichain(dict(offsets)) if offsets is not None else None
        )
        if docs:
            self._runner._enqueue(docs)
        return self.monitor.offsets

    def close(self) -> None:
        self.monitor.on_finish()


class LiveIngestRunner:
    """One maintenance thread absorbing committed documents into a live
    IVF (+ optional forward) index, with the freshness plane attached.

    ``freshness_plane=False`` turns off the histograms, traces, and the
    provider registration — the bench's overhead A/B arm.  The absorb
    path itself is identical either way."""

    def __init__(
        self,
        encoder,
        index,
        forward=None,
        name: str = "live",
        autostart: bool = True,
        freshness_plane: bool = True,
    ):
        self.encoder = encoder
        self.index = index
        self.forward = forward
        self.name = str(name)
        self.freshness_plane = bool(freshness_plane)
        self._cv = threading.Condition()
        self._pending: "deque[_Doc]" = deque()
        self._inflight: List[_Doc] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._connectors: List[IngestConnector] = []
        self._docs_total = 0
        self._batches_total = 0
        self._backpressure_total = 0
        self._dropped_total = 0
        _runners.add(self)
        if self.freshness_plane:
            observe.register_provider(self)
        if autostart:
            self.start()

    # -- connector surface ---------------------------------------------------
    def connector(self, name: Optional[str] = None) -> IngestConnector:
        c = IngestConnector(self, name or f"{self.name}-connector")
        self._connectors.append(c)
        return c

    def ingest_routed(
        self,
        docs: Sequence[Tuple[int, str, int]],
        connector: str = "fleet",
    ) -> int:
        """Owner-routed absorb entry (``serve/fabric.py``): accept
        ``(key, text, t_arrival_ns)`` documents whose arrival stamp was
        taken at the FLEET connector's commit and enqueue them as if a
        local connector had committed them — the freshness plane then
        attributes the full connector→retrievable journey including the
        routing hop, because the clock started at the real commit, not
        at this host's receive."""
        batch = [
            _Doc(int(k), str(t), int(ns), str(connector))
            for k, t, ns in docs
        ]
        if batch:
            self._enqueue(batch)
        return len(batch)

    def _enqueue(self, docs: Sequence[_Doc]) -> None:
        cap = config.get("ingest.queue_cap")
        with self._cv:
            for d in docs:
                # connector commits block past the cap: ingest pressure
                # propagates to the producer, never to unbounded memory
                while len(self._pending) >= cap and not self._stop.is_set():
                    self._cv.wait(0.05)
                self._pending.append(d)
            self._cv.notify_all()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"ingest-{self.name}"
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def __enter__(self) -> "LiveIngestRunner":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every queued document has been absorbed (or
        dropped by a chaos fault) — tests/bench determinism helper."""
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            with self._cv:
                if not self._pending and not self._inflight:
                    return True
            time.sleep(0.002)
        return False

    # -- lag surface (SLO + provider) ---------------------------------------
    def pending_docs(self) -> int:
        with self._cv:
            return len(self._pending) + len(self._inflight)

    def oldest_pending_s(self) -> float:
        now = time.perf_counter_ns()
        with self._cv:
            oldest = None
            if self._pending:
                oldest = self._pending[0].t_arrival_ns
            for d in self._inflight:
                if oldest is None or d.t_arrival_ns < oldest:
                    oldest = d.t_arrival_ns
        if oldest is None:
            return 0.0
        return max(0.0, (now - oldest) * 1e-9)

    def overdue_pending(self, threshold_s: float) -> int:
        """Documents waiting LONGER than the freshness threshold — the
        maintenance-lag term the freshness SLO counts as bad events
        before they ever reach the histogram."""
        cut = time.perf_counter_ns() - int(threshold_s * 1e9)
        with self._cv:
            n = sum(1 for d in self._pending if d.t_arrival_ns < cut)
            n += sum(1 for d in self._inflight if d.t_arrival_ns < cut)
        return n

    @property
    def stats(self) -> Dict[str, Any]:
        return {
            "docs": self._docs_total,
            "batches": self._batches_total,
            "dropped": self._dropped_total,
            "backpressure": self._backpressure_total,
            "pending": self.pending_docs(),
        }

    def observe_metrics(self):
        labels = {"ingest": self.name}
        yield ("gauge", "pathway_ingest_pending_docs", labels,
               float(self.pending_docs()))
        yield ("gauge", "pathway_ingest_oldest_pending_seconds", labels,
               self.oldest_pending_s())
        yield ("counter", "pathway_ingest_docs_total", labels,
               self._docs_total)
        yield ("counter", "pathway_ingest_backpressure_total", labels,
               self._backpressure_total)
        for q in (0.5, 0.99):
            v = _H_FRESH.quantile_s(q)
            if v is not None:
                yield ("gauge", "pathway_freshness_quantile_seconds",
                       {**labels, "q": str(q)}, v)
        for c in self._connectors:
            lag = c.monitor.lag_seconds()
            if lag is not None:
                yield ("gauge", "pathway_ingest_connector_lag_seconds",
                       {**labels, "connector": c.name}, lag)

    # -- the maintenance loop ------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            # scheduler→ingest backpressure: when serve latency is the
            # binding SLO (firing while freshness is quiet), maintenance
            # yields absorb cadence — the serve tier keeps its p99, the
            # backlog ages, and the aging backlog re-arms the freshness
            # burn that eventually wins the yield back
            firing = slo_mod.firing_specs()
            if "serve_latency" in firing and "freshness" not in firing:
                self._backpressure_total += 1
                self._stop.wait(config.get("ingest.backpressure_ms") * 1e-3)
            batch = self._poll()
            if not batch:
                self._stop.wait(config.get("ingest.poll_ms") * 1e-3)
                continue
            try:
                self._absorb(batch)
            finally:
                with self._cv:
                    self._inflight = []
                    self._cv.notify_all()

    def _poll(self) -> List[_Doc]:
        if not _stage_allowed("ingest.poll"):
            # RETRY semantics: the documents never left the queue
            _C_FAIL["poll"].inc()
            self._stop.wait(config.get("ingest.poll_ms") * 1e-3)
            return []
        limit = config.get("ingest.batch_docs")
        with self._cv:
            batch: List[_Doc] = []
            while self._pending and len(batch) < limit:
                batch.append(self._pending.popleft())
            if batch:
                self._inflight = list(batch)
                self._cv.notify_all()
        return batch

    def _drop(self, stage: str, batch: List[_Doc], ctx) -> None:
        """DROP semantics for a faulted embed/commit: only this batch's
        documents are lost (counted per document); serve results stay
        bit-identical because the index simply did not advance."""
        _C_FAIL[stage].inc(len(batch))
        self._dropped_total += len(batch)
        log_once(
            f"ingest.{stage}:fault",
            "ingest %s stage faulted; dropped %d document(s) — counted "
            "on pathway_ingest_failures_total{stage=%s}, serving "
            "continues untouched", stage, len(batch), stage,
        )
        if ctx is not None:
            trace.finish(ctx, statuses=(f"ingest_{stage}_failed",))

    def _absorb(self, batch: List[_Doc]) -> None:
        t_dequeue = time.perf_counter_ns()
        t_oldest = min(d.t_arrival_ns for d in batch)
        ctx = None
        if self.freshness_plane:
            ctx = trace.start_trace("ingest.batch", kind="ingest")
            if ctx is not None:
                # root the trace at the oldest rider's arrival: the root
                # duration IS that document's ingest→retrievable latency
                ctx.t0_ns = t_oldest
                ctx.annotate(
                    docs=len(batch),
                    connectors=sorted({d.connector for d in batch}),
                )
        if not _stage_allowed("ingest.embed"):
            self._drop("embed", batch, ctx)
            return
        texts = [d.text for d in batch]
        keys = [d.key for d in batch]
        try:
            # sequence packing when the encoder offers it (the
            # variable-length ingest hot path; same [B, d] contract)
            enc = getattr(
                self.encoder, "encode_packed_to_device", None
            ) or self.encoder.encode_to_device
            vecs = enc(texts)
        except Exception as exc:
            log_once(
                f"ingest.embed:{type(exc).__name__}",
                "ingest embed failed (%r); dropping batch", exc,
            )
            self._drop("embed", batch, ctx)
            return
        t_embed = time.perf_counter_ns()
        # absorb plan, off every lock: the device→host sync the IVF's
        # own off-lock normalize will consume (value-flow: the sync must
        # not happen under the index lock)
        try:
            host = np.asarray(vecs, np.float32)
        except Exception as exc:
            log_once(
                f"ingest.plan:{type(exc).__name__}",
                "ingest absorb-plan failed (%r); dropping batch", exc,
            )
            self._drop("embed", batch, ctx)
            return
        t_plan = time.perf_counter_ns()
        if not _stage_allowed("ingest.commit"):
            self._drop("commit", batch, ctx)
            return
        try:
            gen_before = getattr(self.index, "generation", None)
            self.index.add(keys, host)
            if self.forward is not None:
                # forward absorb counts its own failures and degrades
                # independently (late-interaction skips those docs)
                self.forward.add(keys, texts)
        except Exception as exc:
            log_once(
                f"ingest.commit:{type(exc).__name__}",
                "ingest commit failed (%r); dropping batch", exc,
            )
            self._drop("commit", batch, ctx)
            return
        t_commit = time.perf_counter_ns()
        # retrievable: the commit bumped the index generation — stamp
        # every rider's freshness and the per-stage attribution
        self._docs_total += len(batch)
        self._batches_total += 1
        if self.freshness_plane:
            for d in batch:
                _H_FRESH.observe_ns(t_commit - d.t_arrival_ns)
                _H_STAGE["queue_wait"].observe_ns(t_dequeue - d.t_arrival_ns)
            _H_STAGE["embed"].observe_ns(t_embed - t_dequeue)
            _H_STAGE["absorb_plan"].observe_ns(t_plan - t_embed)
            _H_STAGE["commit"].observe_ns(t_commit - t_plan)
        if ctx is not None:
            ctx.add_span("ingest.queue_wait", t_oldest, t_dequeue,
                         exemplar=_H_STAGE["queue_wait"])
            ctx.add_span("ingest.embed", t_dequeue, t_embed,
                         exemplar=_H_STAGE["embed"])
            ctx.add_span("ingest.absorb_plan", t_embed, t_plan,
                         exemplar=_H_STAGE["absorb_plan"])
            ctx.add_span("ingest.commit", t_plan, t_commit,
                         exemplar=_H_STAGE["commit"])
            ctx.annotate(
                generation=getattr(self.index, "generation", None),
                generation_before=gen_before,
            )
            threshold_s = config.get("observe.slo_freshness_ms") * 1e-3
            slow = (t_commit - t_oldest) * 1e-9 >= threshold_s
            trace.finish(ctx, force_keep=slow)
