"""Multi-host serve fabric: replica-group admission routing + failover.

The scheduler's replica placement (serve/scheduler.py) spreads batches
over data-parallel pipelines INSIDE one process.  This module is the
tier above it: a front-end that routes admission across HOST-level
replica groups — each host a ``FabricWorker`` wrapping its own serve
scheduler over its own device group — with the failure semantics the
degradation ladder promises (robust/degrade.py):

- **Routing.**  Least-loaded across healthy hosts, with consistent-hash
  affinity by the ``cache/keys.py`` query key: the same query text (at
  the fleet's index generation) lands on the same host while that host
  is healthy and not overloaded past ``PATHWAY_FABRIC_AFFINITY_SLACK``
  in-flight requests of the fleet minimum — so per-host result and
  embedding caches stay hot without a shared cache plane.  The affinity
  key is derived by the SAME ``query_key`` helper the dedup and result
  caches use; the spellings cannot drift.
- **Wire.**  Framed request/response over the exchange plane's
  point-to-point stream (``parallel/exchange.FramedStream``): length-
  prefixed pickle frames behind a 32-byte session secret checked before
  any unpickle, one muxed connection per host carrying requests,
  responses (by ``req_id``), heartbeats, and the ``bye`` drain frame.
- **Failure.**  Per-host circuit breakers (``robust.breaker``):
  heartbeat silence past ``PATHWAY_FABRIC_HEARTBEAT_TIMEOUT``, a
  ``bye``, or a broken stream marks the host down, feeds its breaker,
  fails its in-flight tickets — and the waiting submits RE-ROUTE to a
  surviving host, flagged ``host_failover``.  A dead host costs its
  shards' recall plus a flag, NEVER an exception out of a serve call;
  only an exhausted fleet degrades to an empty ``replica_lost`` result.
  Retry-with-hedge: ``PATHWAY_FABRIC_HEDGE_MS`` > 0 mirrors a request
  to a second healthy host when the first is slow; the first response
  wins (``meta["hedged"]``).
- **Chaos sites** (robust/inject.py): ``fabric.route`` (affinity falls
  back to least-loaded, flagged), ``fabric.send`` / ``fabric.recv``
  (failover to a survivor, breaker fed) — each honors an
  already-spent deadline, so an armed hang releases immediately.
- **Partitioned mode** (``fabric.partitions`` > 0 or ``partitions=``):
  the hosts are no longer replicas — each owns ``doc_key % H`` of the
  corpus per the fleet routing rule (``parallel/shards.py``
  ``FleetPartitionMap``, the same modulo rule as the device-level
  ``ShardGroup.owner_of``).  A serve SCATTERS the query batch to every
  partition over the same framed streams (booked as 1 logical + H
  physical dispatches, ``fabric.scatter``), each host answers with its
  per-partition sorted top-K over ONLY owned candidates (rerank never
  crosses partitions — a document's forward rows live with its
  postings), and the front GATHERS + merges via
  ``ops/topk.tree_merge_topk_host`` re-emitting the owners' exact
  ``(doc, score)`` rows, so an H-way fleet is bit-identical to H=1 on
  the clean path.  A dead/slow partition degrades to the
  ``partition_lost`` rung — the survivors' merge is served, recall is
  lost on the dead partition's keys ONLY, never an exception; the
  straggler bound reuses ``fabric.hedge_ms`` once a first partition
  has answered (plus the hard ``partition.gather_timeout_s``).
  ``absorb()`` / ``connector()`` owner-route committed documents to
  exactly their owning host's ``LiveIngestRunner`` (absorb throughput
  ×H; the arrival stamp taken at connector commit rides the wire so
  connector→retrievable freshness attribution is preserved), and
  ``index_generation()`` reports the fleet generation VECTOR — one
  entry per partition — so the front-side scheduler's dedup and
  result-cache keys (``cache/keys.py``) change when ANY partition
  absorbs.  Chaos sites ``fabric.scatter`` (that partition is lost),
  ``fabric.gather`` (stop waiting: survivors served, stragglers
  flagged), ``partition.absorb`` (that routed batch is dropped +
  counted, re-committable).

Bring-up pairs with ``serve/warmstate.py``: a replacement worker
restores the writer's warm state (same index generation, same cache
keys) before joining — per-partition in partitioned mode, each host
snapshotting only its owned slabs — so a rolling restart under load
serves every request from a surviving host while each worker bounces —
measured by the ``serve_fabric`` / ``partitioned_fabric`` bench phases.
"""

from __future__ import annotations

# pathway: serve-path  (hidden-sync lint applies: no implicit host round trips)

import hashlib
import itertools
import secrets
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import config, observe
from ..cache.keys import query_key
from ..ops.dispatch_counter import record_dispatch, record_fetch
from ..ops.topk import tree_merge_topk_host
from ..parallel.exchange import FramedStream, PeerLost
from ..parallel.shards import FleetPartitionMap
from ..robust import breaker as robust_breaker
from ..robust import inject, log_once
from ..robust.deadline import Deadline
from ..robust.degrade import (
    HOST_FAILOVER,
    PARTITION_LOST,
    REPLICA_LOST,
    ServeResult,
    record_degraded,
)

__all__ = ["FabricWorker", "ServeFabric", "fabric_token"]

_TOKEN_LEN = 32


def fabric_token() -> bytes:
    """Mint one fabric session secret (share it across the replica
    group out-of-band — the spawn layer or the coordination KV)."""
    return secrets.token_bytes(_TOKEN_LEN)


def _generation_of(target) -> int:
    """Best-effort index generation of a serve target: the scheduler's
    ``index_generation`` hook, or the wrapped target's, else 0."""
    seen = set()
    while target is not None and id(target) not in seen:
        seen.add(id(target))
        gen_fn = getattr(target, "index_generation", None)
        if callable(gen_fn):
            try:
                return int(gen_fn())
            except Exception:
                return 0
        target = getattr(target, "target", None)
    return 0


class FabricWorker:
    """One host's serve endpoint: a TCP listener in front of a local
    scheduler (``ServeScheduler`` or anything with ``serve(texts, k=,
    deadline=, priority=) -> ServeResult``).

    Per connection, one reader thread answers ``ping`` inline (pong
    carries the index generation + local in-flight count) and hands
    each ``serve`` frame to its own handler thread — the local
    scheduler's coalescing window then batches concurrent riders
    exactly as it does in-process, so the fabric inherits the 2+2
    per-batch dispatch budget unchanged.  ``stop()`` drains cleanly:
    a ``bye`` frame on every live connection tells front-ends this
    disconnect is a planned restart (re-route, don't panic).

    ``ingest`` (a ``LiveIngestRunner`` or anything with
    ``ingest_routed(docs, connector=)``) enables the partitioned
    fleet's owner-routed ``absorb`` frames: documents arrive with their
    connector-commit arrival stamp and enter this host's OWN ingest
    queue — the front routed them here because this host owns their
    keys, so absorb work fans across the fleet instead of every host
    re-ingesting the full corpus."""

    def __init__(
        self,
        scheduler,
        host: str = "127.0.0.1",
        port: int = 0,
        token: Optional[bytes] = None,
        name: Optional[str] = None,
        ingest=None,
    ):
        self.scheduler = scheduler
        self.ingest = ingest
        self.token = token if token is not None else fabric_token()
        if len(self.token) != _TOKEN_LEN:
            raise ValueError(f"fabric token must be {_TOKEN_LEN} bytes")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self.name = name or f"{self.host}:{self.port}"
        self._lock = threading.Lock()
        self._streams: List[FramedStream] = []
        self._stopping = False
        self._inflight = 0
        self.stats: Dict[str, int] = {
            "requests": 0, "pings": 0, "errors": 0, "absorbs": 0,
        }
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"fabric-acc-{self.name}"
        )
        self._accept_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed (stop())
            if self._stopping:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            try:
                stream = FramedStream.accept(conn, self.token)
            except Exception:
                continue  # junk/unauthenticated: dropped before any pickle
            with self._lock:
                self._streams.append(stream)
            threading.Thread(
                target=self._reader,
                args=(stream,),
                daemon=True,
                name=f"fabric-rd-{self.name}",
            ).start()

    def _reader(self, stream: FramedStream) -> None:
        try:
            while True:
                msg = stream.recv()
                op = msg.get("op")
                if op == "ping":
                    self.stats["pings"] += 1
                    stream.send(
                        {
                            "op": "pong",
                            "generation": _generation_of(self.scheduler),
                            "inflight": self._inflight,
                        }
                    )
                elif op == "serve":
                    threading.Thread(
                        target=self._handle,
                        args=(stream, msg),
                        daemon=True,
                        name=f"fabric-req-{self.name}",
                    ).start()
                elif op == "absorb":
                    threading.Thread(
                        target=self._handle_absorb,
                        args=(stream, msg),
                        daemon=True,
                        name=f"fabric-abs-{self.name}",
                    ).start()
                elif op == "bye":
                    return  # client drained; the close below is clean
        except (PeerLost, Exception):  # noqa: BLE001 - reader dies quietly
            pass
        finally:
            with self._lock:
                if stream in self._streams:
                    self._streams.remove(stream)
            stream.close()

    def _handle(self, stream: FramedStream, msg: Dict[str, Any]) -> None:
        req_id = msg.get("req_id")
        deadline = None
        if msg.get("deadline_ms") is not None:
            deadline = Deadline.after_ms(float(msg["deadline_ms"]))
        with self._lock:
            self._inflight += 1
            self.stats["requests"] += 1
        try:
            kwargs: Dict[str, Any] = {"deadline": deadline}
            if msg.get("priority") is not None:
                kwargs["priority"] = msg["priority"]
            result = self.scheduler.serve(
                msg["texts"], k=msg.get("k"), **kwargs
            )
            degraded = list(getattr(result, "degraded", ()))
            meta = dict(getattr(result, "meta", {}))
            reply = {
                "op": "result",
                "req_id": req_id,
                "rows": [list(r) for r in result],
                "degraded": degraded,
                "meta": meta,
            }
        except Exception as exc:  # the scheduler degrades; a raise is a bug,
            # and it must cost this request a FAILOVER upstream, not silence
            self.stats["errors"] += 1
            reply = {"op": "error", "req_id": req_id, "error": repr(exc)}
        finally:
            with self._lock:
                self._inflight = max(0, self._inflight - 1)
        try:
            stream.send(reply)
        except PeerLost:
            pass  # front-end gone; its failover already covered this request

    def _handle_absorb(self, stream: FramedStream, msg: Dict[str, Any]) -> None:
        """Owner-routed absorb frame: hand the routed documents — their
        arrival stamps taken at the FLEET connector's commit — to this
        host's ingest runner.  A raise becomes an ``error`` reply (the
        front counts the batch dropped on this partition; the docs are
        re-committable), never silence."""
        req_id = msg.get("req_id")
        try:
            if self.ingest is None:
                raise RuntimeError(
                    f"fabric worker {self.name} has no ingest runner"
                )
            docs = [
                (int(k), str(t), int(ns)) for k, t, ns in msg.get("docs", ())
            ]
            accepted = self.ingest.ingest_routed(
                docs, connector=str(msg.get("connector", "fleet"))
            )
            with self._lock:
                self.stats["absorbs"] += 1
            reply: Dict[str, Any] = {
                "op": "absorb_ack",
                "req_id": req_id,
                "accepted": int(accepted),
            }
        except Exception as exc:
            with self._lock:
                self.stats["errors"] += 1
            reply = {"op": "error", "req_id": req_id, "error": repr(exc)}
        try:
            stream.send(reply)
        except PeerLost:
            pass  # front gone; its absorb timeout already counted the drop

    def _close_listener(self) -> None:
        # close() alone frees the fd NUMBER, but with the accept thread
        # blocked inside accept(2) the in-flight syscall pins the kernel
        # socket: it keeps LISTENING on the port, and a "dead" worker
        # silently accepts front-end reconnects (which then pong the
        # heartbeat off a stopped scheduler).  shutdown() tears the
        # socket down underneath the blocked accept — it returns with
        # an error and the port actually closes.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    def kill(self) -> None:
        """Unplanned death (tests / benches / chaos drills): the
        listener and every stream die abruptly — no ``bye`` frame,
        in-flight requests torn mid-reply.  Front-ends observe exactly
        what a killed process looks like: a disconnect, then connection
        refused.  Does not stop the scheduler; the caller owns it."""
        with self._lock:
            self._stopping = True
            streams = list(self._streams)
        self._close_listener()
        for stream in streams:
            stream.close()

    def stop(self) -> None:
        """Planned drain: ``bye`` every front-end (their in-flight
        tickets re-route as failover, new admissions route elsewhere),
        then close the listener and connections.  Idempotent."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            streams = list(self._streams)
        for stream in streams:
            try:
                stream.send({"op": "bye"})
            except PeerLost:
                pass
        self._close_listener()
        for stream in streams:
            stream.close()


class _Pending:
    """One in-flight request on one host link."""

    __slots__ = ("event", "reply")

    def __init__(self):
        self.event = threading.Event()
        self.reply: Optional[Dict[str, Any]] = None

    def resolve(self, reply: Dict[str, Any]) -> None:
        self.reply = reply
        self.event.set()


class _HostLink:
    """Client side of one host: a muxed ``FramedStream`` (one receiver
    thread dispatching replies by ``req_id``), a circuit breaker, and
    the liveness clock the fabric heartbeat drives."""

    def __init__(self, name: str, host: str, port: int, token: bytes):
        self.name = name
        self.host = host
        self.port = int(port)
        self.token = token
        # ONE failure trips the host breaker (a fabric host that broke a
        # stream / went silent / said bye is not worth a retry budget —
        # survivors hold its load), and the cool-down is one heartbeat
        # timeout: a bounced worker is probed again as soon as a restart
        # could plausibly have finished, which is what keeps a rolling
        # restart's re-join latency at heartbeat scale
        self.breaker = robust_breaker(
            f"fabric:{name}",
            failure_threshold=1,
            reset_s=config.get("fabric.heartbeat_timeout_s"),
        )
        self._stream: Optional[FramedStream] = None
        self._conn_lock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self.inflight = 0
        self.last_pong: Optional[float] = None
        self.generation = 0
        self.down_reason: Optional[str] = None

    # -- connection ---------------------------------------------------------
    def ensure(self) -> Optional[FramedStream]:
        """The live stream, connecting if needed; None when the host is
        unreachable (breaker fed by the caller)."""
        with self._conn_lock:
            if self._stream is not None:
                return self._stream
            try:
                stream = FramedStream.connect(
                    self.host,
                    self.port,
                    self.token,
                    timeout=config.get("fabric.connect_timeout_s"),
                )
            except Exception as exc:
                self.down_reason = f"connect: {exc!r}"
                return None
            self._stream = stream
            self.down_reason = None
            self.last_pong = time.monotonic()
            threading.Thread(
                target=self._receiver,
                args=(stream,),
                daemon=True,
                name=f"fabric-recv-{self.name}",
            ).start()
            return stream

    def _receiver(self, stream: FramedStream) -> None:
        try:
            while True:
                msg = stream.recv()
                op = msg.get("op")
                if op == "pong":
                    self.last_pong = time.monotonic()
                    self.generation = int(msg.get("generation", 0))
                elif op in ("result", "error", "absorb_ack"):
                    self.last_pong = time.monotonic()
                    with self._plock:
                        pending = self._pending.pop(msg.get("req_id"), None)
                        if pending is not None:
                            self.inflight = max(0, self.inflight - 1)
                    if pending is not None:
                        pending.resolve(msg)
                elif op == "bye":
                    self.mark_down("bye")
                    return
        except Exception:  # noqa: BLE001 - disconnect = down
            self.mark_down("disconnect")
        finally:
            with self._conn_lock:
                if self._stream is stream:
                    self._stream = None
            stream.close()

    def mark_down(self, reason: str) -> None:
        """Host is gone (bye / disconnect / heartbeat silence): feed the
        breaker, drop the stream, FAIL every in-flight ticket — their
        waiting submits observe the failure and re-route."""
        with self._conn_lock:
            stream, self._stream = self._stream, None
        if stream is None and self.down_reason is not None:
            # already down (e.g. the heartbeat closed the stream and the
            # receiver died seeing it): the FIRST reason stands, and the
            # breaker is not fed twice — a stale echo must not reopen a
            # half-open probe
            return
        self.down_reason = reason
        self.breaker.record_failure()
        if stream is not None:
            stream.close()
        with self._plock:
            pending, self._pending = self._pending, {}
            self.inflight = 0
        for p in pending.values():
            p.resolve({"op": "error", "error": f"host {self.name} {reason}"})

    def up(self) -> bool:
        return self._stream is not None

    def usable(self) -> bool:
        """Routable: breaker not open.  Deliberately reads ``state``,
        not ``allow()`` — listing candidates must not consume the one
        half-open probe slot; ``ServeFabric`` gates the actual attempt
        with ``allow()`` at launch time."""
        return self.breaker.state != "open"

    # -- requests -----------------------------------------------------------
    def send_request(
        self, req_id: int, msg: Dict[str, Any], deadline=None
    ) -> _Pending:
        """Register + send one request frame; raises on any failure
        (chaos site ``fabric.send``, dead stream) — the caller fails
        over."""
        stream = self.ensure()
        if stream is None:
            raise PeerLost(f"host {self.name} unreachable")
        pending = _Pending()
        with self._plock:
            self._pending[req_id] = pending
            self.inflight += 1
        try:
            inject.fire("fabric.send", deadline=deadline)
            stream.send(msg)
        except BaseException:
            with self._plock:
                if self._pending.pop(req_id, None) is not None:
                    self.inflight = max(0, self.inflight - 1)
            raise
        return pending

    def cancel(self, req_id: int) -> None:
        """Forget an in-flight request the caller stopped waiting for
        (gather straggler / absorb timeout) — a late reply to a
        cancelled id is dropped by the receiver instead of leaking a
        pending slot forever."""
        with self._plock:
            if self._pending.pop(req_id, None) is not None:
                self.inflight = max(0, self.inflight - 1)

    def heartbeat(self, timeout_s: float) -> None:
        """One heartbeat tick: ping if connected; silence past
        ``timeout_s`` marks the host down (failing its in-flight
        tickets into re-routes)."""
        stream = self._stream
        if stream is None:
            return
        last = self.last_pong
        if last is not None and time.monotonic() - last > timeout_s:
            self.mark_down("heartbeat_silence")
            return
        try:
            stream.send({"op": "ping"})
        except PeerLost:
            self.mark_down("disconnect")

    def close(self) -> None:
        with self._conn_lock:
            stream, self._stream = self._stream, None
        if stream is not None:
            try:
                stream.send({"op": "bye"})
            except PeerLost:
                pass
            stream.close()


class ServeFabric:
    """The front-end: admission routing across a replica group.

    ``hosts`` maps a host name to its ``(host, port)`` address (or a
    ``"host:port"`` string); all workers share ``token``.  The serve
    surface mirrors ``ServeScheduler`` — ``submit() -> ticket``,
    ``serve()``/``__call__`` — so callers swap tiers without code
    changes, and the failure contract is the ladder's: a response is
    ALWAYS a ``ServeResult``, possibly flagged ``host_failover`` or
    (fleet exhausted) empty ``replica_lost``, never an exception.

    ``partitions`` (default: the ``fabric.partitions`` knob; 0 keeps
    replica mode) switches the hosts from replicas to PARTITIONS of one
    index: partition ``i`` is the ``i``-th host in ``hosts`` insertion
    order and owns ``doc_key % H`` per ``FleetPartitionMap``.  Serves
    scatter-gather with the ``partition_lost`` ladder rung; ``absorb``
    / ``connector`` owner-route ingest; ``index_generation()`` reports
    the per-partition generation vector."""

    def __init__(
        self,
        hosts: Dict[str, Any],
        token: bytes,
        name: Optional[str] = None,
        partitions: Optional[int] = None,
    ):
        if not hosts:
            raise ValueError("ServeFabric needs at least one host")
        self.name = name or "fabric"
        self._links: List[_HostLink] = []
        for host_name, addr in hosts.items():
            if isinstance(addr, str):
                h, p = addr.rsplit(":", 1)
            else:
                h, p = addr
            self._links.append(_HostLink(str(host_name), h, int(p), token))
        n_parts = (
            int(partitions)
            if partitions is not None
            else config.get("fabric.partitions")
        )
        self.partition_map: Optional[FleetPartitionMap] = None
        if n_parts:
            if n_parts != len(self._links):
                raise ValueError(
                    f"fabric.partitions={n_parts} but {len(self._links)} "
                    "hosts: in partitioned mode every host IS one "
                    "partition (partition i = i-th host)"
                )
            self.partition_map = FleetPartitionMap(n_parts)
        self._req_ids = itertools.count(1)
        self.stats: Dict[str, int] = {
            "requests": 0,
            "ok": 0,
            "failover": 0,
            "hedged": 0,
            "lost": 0,
            "partition_lost": 0,
        }
        # per-partition accounting (partitioned mode): lost serves and
        # owner-routed absorb outcomes, keyed by partition index
        n_hosts = len(self._links)
        self._part_lost: List[int] = [0] * n_hosts
        self._absorb_docs: List[int] = [0] * n_hosts
        self._absorb_dropped: List[int] = [0] * n_hosts
        self._stats_lock = threading.Lock()
        self._observe_id = observe.next_id()
        observe.register_provider(self)
        self._closed = False
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name=f"{self.name}-hb"
        )
        self._hb_thread.start()

    # -- liveness ------------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._closed:
            time.sleep(config.get("fabric.heartbeat_s"))
            if self._closed:
                return
            timeout_s = config.get("fabric.heartbeat_timeout_s")
            for link in self._links:
                link.heartbeat(timeout_s)

    def connect(self) -> int:
        """Eagerly dial every host (optional — routing connects
        lazily); returns how many are reachable."""
        return sum(1 for link in self._links if link.ensure() is not None)

    @property
    def generation(self) -> int:
        """The fleet's index generation as last reported by pongs (the
        routing-affinity generation)."""
        return max((link.generation for link in self._links), default=0)

    @property
    def partitioned(self) -> bool:
        return self.partition_map is not None

    def index_generation(self):
        """The generation a front-side scheduler keys dedup/cache on
        (``cache/keys.py`` normalizes it): in partitioned mode the fleet
        generation VECTOR — one entry per partition, so an absorb on ANY
        partition changes the key and a result cached via host A can
        never outlive host B's absorb — else the replica-mode scalar."""
        if self.partition_map is not None:
            return tuple(link.generation for link in self._links)
        return self.generation

    def poll_generations(self, timeout_s: float = 1.0):
        """Ping every host and wait for fresh pongs, then return
        ``index_generation()`` — the tests/bench helper that observes an
        absorb's generation bump without waiting out a heartbeat tick."""
        marks = []
        for link in self._links:
            marks.append(link.last_pong)
            stream = link.ensure()
            if stream is None:
                continue
            try:
                stream.send({"op": "ping"})
            except PeerLost:
                link.mark_down("disconnect")
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            if all(
                not link.up() or link.last_pong != mark
                for link, mark in zip(self._links, marks)
            ):
                break
            time.sleep(0.002)
        return self.index_generation()

    # -- routing -------------------------------------------------------------
    def _affinity(self, text: str) -> int:
        key_text, gen = query_key(text, self.generation)
        digest = hashlib.blake2b(
            f"{gen}\x00{key_text}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(digest, "big") % len(self._links)

    def _route(self, texts: Sequence[str], deadline=None) -> Tuple[List[int], bool]:
        """Candidate host indices in preference order + whether routing
        itself degraded (chaos site ``fabric.route``: affinity is an
        optimization, so a route fault falls back to pure least-loaded,
        flagged)."""
        degraded = False
        aff: Optional[int] = None
        try:
            inject.fire("fabric.route", deadline=deadline)
            if texts:
                aff = self._affinity(str(texts[0]))
        except Exception as exc:
            degraded = True
            log_once(
                f"fabric.route:{type(exc).__name__}",
                "fabric routing degraded (%r); using least-loaded host",
                exc,
            )
        usable = [i for i, link in enumerate(self._links) if link.usable()]
        order: List[int] = []
        if aff is not None and aff in usable:
            slack = config.get("fabric.affinity_slack")
            min_inflight = min(self._links[i].inflight for i in usable)
            if self._links[aff].inflight <= min_inflight + slack:
                order.append(aff)
        order.extend(
            sorted(
                (i for i in usable if i not in order),
                key=lambda i: (self._links[i].inflight, i),
            )
        )
        return order, degraded

    # -- serve surface -------------------------------------------------------
    def submit(
        self,
        texts: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
        priority: Optional[str] = None,
    ):
        """Admit one request; returns a zero-arg-callable ticket
        (``result(timeout)`` honored for API parity) resolving to the
        ``ServeResult``.  Routing, send, hedge, and failover all run on
        the WAITER's thread — an in-flight ticket whose host dies is
        re-routed right there, inside the same call."""
        texts = list(texts)
        box: List[Any] = [None]

        def run() -> ServeResult:
            if box[0] is None:
                box[0] = self._serve_once(texts, k, deadline, priority)
            return box[0]

        class _FabricTicket:
            __slots__ = ()

            def __call__(self) -> ServeResult:
                return run()

            def result(self, timeout: Optional[float] = None) -> ServeResult:
                return run()

        return _FabricTicket()

    def serve(
        self,
        texts: Sequence[str],
        k: Optional[int] = None,
        deadline: Optional[Deadline] = None,
        priority: Optional[str] = None,
    ) -> ServeResult:
        return self._serve_once(list(texts), k, deadline, priority)

    __call__ = serve

    def _serve_once(
        self,
        texts: List[str],
        k: Optional[int],
        deadline: Optional[Deadline],
        priority: Optional[str],
    ) -> ServeResult:
        if self.partition_map is not None:
            return self._serve_scatter(texts, k, deadline, priority)
        with self._stats_lock:
            self.stats["requests"] += 1
        order, route_degraded = self._route(texts, deadline=deadline)
        failover = route_degraded
        hedged = False
        hedge_s = config.get("fabric.hedge_ms") * 1e-3
        base_msg = {
            "op": "serve",
            "texts": texts,
            "k": k,
            "priority": priority,
            "deadline_ms": (
                max(0.0, deadline.remaining_s() * 1e3)
                if deadline is not None
                else None
            ),
        }
        attempts: List[Tuple[_HostLink, _Pending]] = []

        def launch(idx: int) -> bool:
            link = self._links[idx]
            if not link.breaker.allow():
                return False  # opened since routing, or probe slot taken
            req_id = next(self._req_ids)
            try:
                pending = link.send_request(
                    req_id, {**base_msg, "req_id": req_id}, deadline=deadline
                )
            except BaseException as exc:  # noqa: BLE001 - failover, never raise
                link.breaker.record_failure()
                log_once(
                    f"fabric.send:{link.name}:{type(exc).__name__}",
                    "fabric send to host %s failed (%r); failing over",
                    link.name,
                    exc,
                )
                return False
            attempts.append((link, pending))
            return True

        queue = list(order)
        while queue and not attempts:
            if not launch(queue.pop(0)):
                failover = True
        if not attempts:
            return self._lost(texts, route_degraded)

        # wait for the first reply, hedging to the next host when the
        # primary is slow; a failed attempt (host died mid-flight, recv
        # chaos) re-routes to the next candidate — all on this thread
        timeout_s = config.get("fabric.request_timeout_s")
        t_end = time.monotonic() + timeout_s
        if deadline is not None:
            t_end = min(t_end, time.monotonic() + max(0.0, deadline.remaining_s()))
        hedge_at = (
            time.monotonic() + hedge_s if hedge_s > 0 and queue else None
        )
        try:
            inject.fire("fabric.recv", deadline=deadline)
        except BaseException as exc:  # noqa: BLE001 - recv chaos = failover
            failover = True
            for link, pending in attempts:
                link.breaker.record_failure()
            log_once(
                f"fabric.recv:{type(exc).__name__}",
                "fabric recv degraded (%r); failing over",
                exc,
            )
            attempts.clear()
            while queue and not attempts:
                if not launch(queue.pop(0)):
                    pass
            if not attempts:
                return self._lost(texts, True)
        while True:
            now = time.monotonic()
            for link, pending in list(attempts):
                if not pending.event.is_set():
                    continue
                reply = pending.reply or {}
                if reply.get("op") == "result":
                    link.breaker.record_success()
                    return self._finish(
                        reply, link, failover, hedged, route_degraded
                    )
                # error reply (host down / worker bug): drop this
                # attempt, feed the breaker, re-route
                attempts.remove((link, pending))
                failover = True
                if reply.get("req_id") is not None:
                    # the WORKER answered with an error (its scheduler
                    # raised — a stopped replica or a worker bug): that
                    # host is sick even though its socket is healthy,
                    # so its breaker must open.  Synthetic errors from
                    # mark_down() carry no req_id and already fed the
                    # breaker exactly once there.
                    link.breaker.record_failure()
                log_once(
                    f"fabric.error:{link.name}",
                    "fabric host %s failed a request (%s); failing over",
                    link.name,
                    reply.get("error", "?"),
                )
            if not attempts:
                launched = False
                while queue and not launched:
                    launched = launch(queue.pop(0))
                if not launched:
                    return self._lost(texts, route_degraded)
                continue
            if hedge_at is not None and now >= hedge_at:
                hedge_at = None
                launched = False
                while queue and not launched:
                    launched = launch(queue.pop(0))
                if launched:
                    hedged = True
                    with self._stats_lock:
                        self.stats["hedged"] += 1
            if now >= t_end:
                # the fleet is slow past the budget: feed every slow
                # host's breaker and degrade — never an exception
                for link, _p in attempts:
                    link.breaker.record_failure()
                return self._lost(texts, route_degraded, timeout=True)
            wait_s = min(0.01, max(0.0, t_end - now))
            if hedge_at is not None:
                wait_s = min(wait_s, max(0.0, hedge_at - now))
            attempts[0][1].event.wait(wait_s)

    def _finish(
        self,
        reply: Dict[str, Any],
        link: _HostLink,
        failover: bool,
        hedged: bool,
        route_degraded: bool,
    ) -> ServeResult:
        result = ServeResult(
            reply.get("rows", []),
            degraded=reply.get("degraded", ()),
            meta=reply.get("meta", {}),
        )
        extra_meta: Dict[str, Any] = {"fabric_host": link.name}
        extra_flags: Tuple[str, ...] = ()
        if failover:
            extra_flags = (HOST_FAILOVER,)
            record_degraded(HOST_FAILOVER)
            with self._stats_lock:
                self.stats["failover"] += 1
        else:
            with self._stats_lock:
                self.stats["ok"] += 1
        if hedged:
            extra_meta["hedged"] = True
        if route_degraded:
            extra_meta["route_degraded"] = True
        return result.with_flags(extra_flags, extra_meta)

    def _lost(
        self,
        texts: List[str],
        route_degraded: bool,
        timeout: bool = False,
    ) -> ServeResult:
        """No healthy host: the fleet, not the request, is the outage —
        an empty FLAGGED result (counted), never an exception."""
        record_degraded(REPLICA_LOST)
        with self._stats_lock:
            self.stats["lost"] += 1
        meta: Dict[str, Any] = {"fabric": "no_healthy_host"}
        if timeout:
            meta["fabric"] = "fleet_timeout"
        if route_degraded:
            meta["route_degraded"] = True
        return ServeResult(
            [[] for _ in texts], degraded=(REPLICA_LOST,), meta=meta
        )

    # -- partitioned scatter-gather -------------------------------------------
    def _serve_scatter(
        self,
        texts: List[str],
        k: Optional[int],
        deadline: Optional[Deadline],
        priority: Optional[str],
    ) -> ServeResult:
        """Partitioned serve: fan the batch to every partition (ONE
        logical dispatch fanning H physical sends), gather each
        partition's sorted top-K over its owned candidates, merge
        front-side.  A partition that cannot be reached, answers with an
        error, or straggles past the hedge/gather budget is LOST — the
        survivors' merge is served flagged ``partition_lost`` (recall
        lost on that partition's keys only), never an exception."""
        with self._stats_lock:
            self.stats["requests"] += 1
        n_parts = len(self._links)
        base_msg = {
            "op": "serve",
            "texts": texts,
            "k": k,
            "priority": priority,
            "deadline_ms": (
                max(0.0, deadline.remaining_s() * 1e3)
                if deadline is not None
                else None
            ),
        }
        # the same booking rule the sharded index uses for per-shard
        # device dispatches: 1 logical + H physical
        record_dispatch("fabric.scatter", shards=n_parts)
        pending_by_part: Dict[int, Tuple[int, _Pending]] = {}
        lost: Dict[int, str] = {}
        for part, link in enumerate(self._links):
            if not link.breaker.allow():
                lost[part] = "breaker_open"
                continue
            req_id = next(self._req_ids)
            try:
                inject.fire("fabric.scatter", deadline=deadline)
                pending_by_part[part] = (
                    req_id,
                    link.send_request(
                        req_id,
                        {**base_msg, "req_id": req_id},
                        deadline=deadline,
                    ),
                )
            except BaseException as exc:  # noqa: BLE001 - lost, never raise
                link.breaker.record_failure()
                log_once(
                    f"fabric.scatter:{link.name}:{type(exc).__name__}",
                    "fabric scatter to partition %s failed (%r); serving "
                    "without it",
                    link.name,
                    exc,
                )
                lost[part] = "send"
        replies: Dict[int, Dict[str, Any]] = {}
        gather_fault = False
        try:
            inject.fire("fabric.gather", deadline=deadline)
        except BaseException as exc:  # noqa: BLE001 - stop waiting, serve
            gather_fault = True
            log_once(
                f"fabric.gather:{type(exc).__name__}",
                "fabric gather degraded (%r); serving resolved partitions",
                exc,
            )
        if not gather_fault and pending_by_part:
            hedge_s = config.get("fabric.hedge_ms") * 1e-3
            timeout_s = min(
                config.get("fabric.request_timeout_s"),
                config.get("partition.gather_timeout_s"),
            )
            t_end = time.monotonic() + timeout_s
            if deadline is not None:
                t_end = min(
                    t_end,
                    time.monotonic() + max(0.0, deadline.remaining_s()),
                )
            first_t: Optional[float] = None
            while pending_by_part:
                for part, (req_id, pending) in list(pending_by_part.items()):
                    if not pending.event.is_set():
                        continue
                    replies[part] = pending.reply or {}
                    del pending_by_part[part]
                    if first_t is None:
                        first_t = time.monotonic()
                if not pending_by_part:
                    break
                now = time.monotonic()
                if now >= t_end:
                    # hard straggler budget (partition.gather_timeout_s
                    # / the request deadline): a host slow past the
                    # fleet's patience is sick — feed its breaker so
                    # the next serve skips it immediately
                    for part, (req_id, _p) in pending_by_part.items():
                        self._links[part].breaker.record_failure()
                        self._links[part].cancel(req_id)
                        lost[part] = "timeout"
                    pending_by_part.clear()
                    break
                if (
                    hedge_s > 0
                    and first_t is not None
                    and now >= first_t + hedge_s
                ):
                    # soft straggler bound reusing fabric.hedge_ms: one
                    # partition has answered and the hedge budget is
                    # spent — serve without the stragglers (breakers
                    # NOT fed; slow-once is not sick)
                    for part, (req_id, _p) in pending_by_part.items():
                        self._links[part].cancel(req_id)
                        lost[part] = "straggler"
                    pending_by_part.clear()
                    break
                wait_s = min(0.01, max(0.0, t_end - now))
                if hedge_s > 0 and first_t is not None:
                    wait_s = min(wait_s, max(0.0005, first_t + hedge_s - now))
                next(iter(pending_by_part.values()))[1].event.wait(wait_s)
        # a gather fault stops the wait: partitions already resolved
        # survive, the rest are lost — their hosts are NOT sick (the
        # front's collect path was), so their breakers are not fed
        for part, (req_id, pending) in list(pending_by_part.items()):
            if pending.event.is_set():
                replies[part] = pending.reply or {}
            else:
                self._links[part].cancel(req_id)
                lost[part] = "gather"
        pending_by_part.clear()
        part_rows: Dict[int, List[Any]] = {}
        gen_vector: List[int] = [link.generation for link in self._links]
        degraded: List[str] = []
        for part in sorted(replies):
            reply = replies[part]
            if reply.get("op") == "result":
                self._links[part].breaker.record_success()
                part_rows[part] = reply.get("rows", [])
                degraded.extend(reply.get("degraded", ()))
                rmeta = reply.get("meta", {})
                if rmeta.get("index_generation") is not None:
                    # dispatch-time generation from the owner itself —
                    # fresher than the last pong's
                    gen_vector[part] = int(rmeta["index_generation"])
            else:
                if reply.get("req_id") is not None:
                    # the WORKER answered with an error: that partition
                    # host is sick even though its socket is healthy
                    self._links[part].breaker.record_failure()
                log_once(
                    f"fabric.partition:{self._links[part].name}",
                    "partition %s failed a scatter request (%s); serving "
                    "without it",
                    self._links[part].name,
                    reply.get("error", "?"),
                )
                lost[part] = "error"
        record_fetch("fabric.gather", shards=max(1, len(part_rows)))
        rows = self._merge_partitions(texts, part_rows, k)
        meta: Dict[str, Any] = {
            "fabric_partitions": n_parts,
            "index_generation": tuple(gen_vector),
        }
        if lost:
            record_degraded(PARTITION_LOST, len(lost))
            degraded.append(PARTITION_LOST)
            meta["partitions_lost"] = {
                self._links[p].name: why for p, why in sorted(lost.items())
            }
        with self._stats_lock:
            if lost:
                self.stats["partition_lost"] += 1
                for part in lost:
                    self._part_lost[part] += 1
            if part_rows:
                self.stats["ok"] += 1
            else:
                self.stats["lost"] += 1
        return ServeResult(rows, degraded=degraded, meta=meta)

    def _merge_partitions(
        self,
        texts: List[str],
        part_rows: Dict[int, List[Any]],
        k: Optional[int],
    ) -> List[List[Any]]:
        """Front-side merge of per-partition sorted top-K rows via the
        SAME primitive the device shards use
        (``ops/topk.tree_merge_topk_host``): scores order the merge,
        then the owners' original ``(doc, score)`` pairs are re-emitted
        — the merge only PICKS, never recomputes, which is what makes
        an H-way fleet bit-identical to H=1 on the clean path."""
        if not part_rows:
            return [[] for _ in texts]
        parts = sorted(part_rows)
        b = len(texts)
        k_cap = 0
        for p in parts:
            for row in part_rows[p]:
                k_cap = max(k_cap, len(row))
        k_out = int(k) if k else k_cap
        if k_cap == 0 or k_out == 0:
            return [[] for _ in texts]
        s = len(parts)
        # [S, B, K] merge inputs: scores order; (owner, position) name
        # the original pair to re-emit; absent slots (a partition that
        # returned fewer than K rows) mask to -inf and are filtered out
        scores = np.full((s, b, k_cap), -np.inf, dtype=np.float64)
        pos = np.zeros((s, b, k_cap), dtype=np.int64)
        owner = np.zeros((s, b, k_cap), dtype=np.int64)
        for si, p in enumerate(parts):
            owner[si, :, :] = si
            rows = part_rows[p]
            for qi in range(b):
                row = rows[qi] if qi < len(rows) else []
                for j, pair in enumerate(row[:k_cap]):
                    scores[si, qi, j] = float(pair[1])
                    pos[si, qi, j] = j
        m_scores, m_owner, m_pos = tree_merge_topk_host(
            scores, owner, pos, k_out
        )
        out: List[List[Any]] = []
        for qi in range(b):
            merged_row: List[Any] = []
            for j in range(m_scores.shape[1]):
                if not np.isfinite(m_scores[qi, j]):
                    continue
                p = parts[int(m_owner[qi, j])]
                merged_row.append(part_rows[p][qi][int(m_pos[qi, j])])
            out.append(merged_row)
        return out

    # -- owner-routed absorb --------------------------------------------------
    def connector(self, name: Optional[str] = None) -> "_FleetConnector":
        """A fleet-side ingest connector (mirrors
        ``serve/ingest.IngestConnector``): buffer keyed rows, stamp them
        at ``commit()`` — the SAME arrival clock — then owner-route each
        document to exactly its owning partition."""
        if self.partition_map is None:
            raise RuntimeError("connector() requires a partitioned fabric")
        return _FleetConnector(self, name or f"{self.name}-connector")

    def absorb(
        self,
        docs: Sequence[Tuple[int, str, int]],
        deadline: Optional[Deadline] = None,
        connector: str = "fleet",
    ) -> int:
        """Owner-routed absorb: route ``(key, text, t_arrival_ns)``
        documents to their owning partitions ONLY (``FleetPartitionMap``
        buckets — each host ingests 1/H of the stream, so fleet absorb
        throughput scales ×H) and wait for the owners' acks.  A
        partition that faults (chaos site ``partition.absorb``), is
        unreachable, errors, or misses ``partition.absorb_timeout_s``
        has its routed batch counted dropped — the documents are
        re-committable, the commit never raises.  Returns accepted."""
        if self.partition_map is None:
            raise RuntimeError("absorb() requires a partitioned fabric")
        docs = [(int(kk), str(t), int(ns)) for kk, t, ns in docs]
        if not docs:
            return 0
        buckets = self.partition_map.route([d[0] for d in docs])
        acks: List[Tuple[int, int, List[Tuple[int, str, int]], _Pending]] = []
        for part in sorted(buckets):
            batch = [docs[i] for i in buckets[part]]
            link = self._links[part]
            try:
                inject.fire("partition.absorb", deadline=deadline)
                if not link.breaker.allow():
                    raise PeerLost(f"partition {link.name} breaker open")
                req_id = next(self._req_ids)
                pending = link.send_request(
                    req_id,
                    {
                        "op": "absorb",
                        "req_id": req_id,
                        "docs": batch,
                        "connector": connector,
                    },
                    deadline=deadline,
                )
            except BaseException as exc:  # noqa: BLE001 - dropped, never raise
                with self._stats_lock:
                    self._absorb_dropped[part] += len(batch)
                log_once(
                    f"partition.absorb:{link.name}:{type(exc).__name__}",
                    "absorb route to partition %s failed (%r); batch "
                    "dropped (re-committable)",
                    link.name,
                    exc,
                )
                continue
            acks.append((part, req_id, batch, pending))
        timeout_s = config.get("partition.absorb_timeout_s")
        t_end = time.monotonic() + timeout_s
        if deadline is not None:
            t_end = min(
                t_end, time.monotonic() + max(0.0, deadline.remaining_s())
            )
        accepted = 0
        for part, req_id, batch, pending in acks:
            pending.event.wait(max(0.0, t_end - time.monotonic()))
            reply = pending.reply or {}
            if pending.event.is_set() and reply.get("op") == "absorb_ack":
                n = int(reply.get("accepted", len(batch)))
                accepted += n
                self._links[part].breaker.record_success()
                with self._stats_lock:
                    self._absorb_docs[part] += n
            else:
                self._links[part].cancel(req_id)
                if pending.event.is_set() and reply.get("req_id") is not None:
                    # the OWNER answered with an error (no runner / a
                    # runner bug): that host is sick, feed its breaker
                    self._links[part].breaker.record_failure()
                with self._stats_lock:
                    self._absorb_dropped[part] += len(batch)
                log_once(
                    f"partition.absorb_ack:{self._links[part].name}",
                    "partition %s did not ack an absorb batch (%s); "
                    "batch dropped (re-committable)",
                    self._links[part].name,
                    reply.get("error", "timeout"),
                )
        return accepted

    # -- flight recorder ------------------------------------------------------
    def observe_metrics(self):
        base = {"fabric": self.name, "id": str(self._observe_id)}
        for outcome in ("ok", "failover", "hedged", "lost"):
            yield (
                "counter",
                "pathway_fabric_requests_total",
                {**base, "outcome": outcome},
                self.stats[outcome],
            )
        for link in self._links:
            labels = {**base, "host": link.name}
            yield ("gauge", "pathway_fabric_host_up", labels, int(link.up()))
            yield (
                "gauge", "pathway_fabric_inflight", labels, link.inflight
            )
        if self.partition_map is not None:
            yield (
                "gauge",
                "pathway_partition_count",
                base,
                self.partition_map.n_partitions,
            )
            for part, link in enumerate(self._links):
                pl = {**base, "partition": str(part), "host": link.name}
                yield (
                    "counter",
                    "pathway_partition_lost_total",
                    pl,
                    self._part_lost[part],
                )
                yield (
                    "counter",
                    "pathway_partition_absorb_docs_total",
                    pl,
                    self._absorb_docs[part],
                )
                yield (
                    "counter",
                    "pathway_partition_absorb_dropped_total",
                    pl,
                    self._absorb_dropped[part],
                )

    def stop(self) -> None:
        """Close every link (bye frames, best-effort).  Idempotent."""
        if self._closed:
            return
        self._closed = True
        for link in self._links:
            link.close()


class _FleetConnector:
    """Fleet-side twin of ``serve/ingest.IngestConnector``: the same
    buffer/commit surface, but ``commit()`` owner-routes the batch over
    the fabric instead of enqueueing locally.  The arrival stamp is
    taken HERE — at connector commit, exactly where the single-host
    connector takes it — and rides the wire, so the owner's freshness
    histograms attribute the full connector→retrievable journey
    including the routing hop."""

    def __init__(self, fabric: ServeFabric, name: str):
        self._fabric = fabric
        self.name = str(name)
        self._buf: List[Tuple[int, str]] = []
        self._lock = threading.Lock()

    def insert(self, key: int, text: str) -> None:
        with self._lock:
            self._buf.append((int(key), str(text)))

    def insert_rows(self, rows) -> None:
        rows = [(int(k), str(t)) for k, t in rows]
        with self._lock:
            self._buf.extend(rows)

    def commit(self, deadline: Optional[Deadline] = None) -> int:
        """Commit buffered rows to their owning partitions; returns how
        many documents the owners accepted (a faulted/dead partition's
        batch counts dropped on the fabric's absorb ledger and is
        re-committable — commit itself never raises)."""
        with self._lock:
            rows, self._buf = self._buf, []
        if not rows:
            return 0
        t = time.perf_counter_ns()
        return self._fabric.absorb(
            [(k, txt, t) for k, txt in rows],
            deadline=deadline,
            connector=self.name,
        )

    def close(self) -> None:
        pass
