"""The online knob tuner — the profile-guided loop, closed.

Every signal the observe plane exports (queue-wait histograms, pack
occupancy counters, cache hit/eviction counters, profiler share-of-wall)
already *describes* the knob that would fix it; this module is the small
controller that actually turns those knobs, bounded by the declarative
registry (``pathway_tpu/config.py``):

- ``serve.coalesce_us`` — from queue wait vs SLO headroom: a firing
  fast-burn window shrinks the coalescing window (latency pressure
  beats batching efficiency); ample headroom with the window binding
  (mean wait ~= window) grows it.
- ``decode.step_bucket`` — from decode-chunk occupancy: mostly-idle
  chunks halve the bucket, saturated chunks double it.
- ``cache.{result,embed,kv}_bytes`` — from marginal hit rate: a tier
  evicting while hits still climb is budget-bound (grow); a tier whose
  hits flatlined well under budget gives HBM back (shrink).  Applied to
  the registry AND retargeted onto every live ``CacheTier``.
- ``observe.profile_sample`` — from overhead share: sampling cost above
  ~1% of wall halves the fraction; negligible cost doubles it back.

Safety rails, in order:

1. **The registry is the authority.**  Every write goes through
   ``config.set``: clamped to the declared bounds, and ``static``-class
   knobs (everything a bit-identity oracle pins) raise
   ``StaticKnobError`` — the tuner counts the veto and moves on.  A
   controller bug cannot un-pin determinism.
2. **Reversible.**  Every adjustment is journaled; ``revert()`` restores
   the pre-tuner state (env/default layer), including live tier budgets.
3. **Degrade, never fail.**  The ``tuner.adjust`` chaos site is fired
   inside the tick; an injected fault reverts everything, freezes the
   tuner, and counts ``pathway_tuner_faults_total`` — a broken
   controller leaves the system exactly where static config had it.
4. **Observable.**  ``pathway_tuner_adjustments_total{knob,direction}``,
   ``pathway_tuner_vetoed_total``, ``pathway_tuner_faults_total``,
   and ``pathway_tuner_value{knob}`` gauges render on ``/metrics``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .. import config, observe
from ..config import StaticKnobError
from ..robust import inject

__all__ = ["Tuner", "tuner_from_env"]

# knob the tuner writes for each cache tier name (store.py labels)
_TIER_KNOB = {
    "result": "cache.result_bytes",
    "embedding": "cache.embed_bytes",
    "generator_kv": "cache.kv_bytes",
}

# controller constants: gentle multiplicative steps — the registry
# clamps are the hard bounds, these keep single ticks small enough to
# revert cheaply
_GROW = 1.25
_SHRINK = 0.8
_OCC_LOW = 0.5       # decode chunk occupancy below this: bucket too wide
_OCC_HIGH = 0.85     # above this: bucket saturating, room to widen
_PROFILE_OVERHEAD_HIGH = 0.01   # sampling cost > 1% of wall: back off
_PROFILE_OVERHEAD_LOW = 0.001   # < 0.1%: cheap enough to sample more
_PROFILE_SAMPLE_COST_S = 5e-6   # per-sample bookkeeping estimate


class Tuner:
    """Background controller over the registry's ``dynamic`` knobs.

    ``tick()`` is the whole control loop (call it directly in tests);
    ``start()`` runs it on a daemon thread every ``interval_s``."""

    def __init__(self, interval_s: Optional[float] = None):
        if interval_s is None:
            interval_s = config.get("tuner.interval_s")
        self.interval_s = float(interval_s)
        # journal of (knob, had_override, previous_override) in apply
        # order — revert() unwinds it newest-first
        self._journal: List[Tuple[str, bool, Any]] = []
        self._journaled: set = set()
        self._tier_bytes0: Dict[int, Tuple[Any, int]] = {}
        self._frozen = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # last-tick signal snapshots (deltas drive the controllers)
        self._last: Dict[str, Any] = {}
        self.stats = {"ticks": 0, "adjustments": 0, "vetoes": 0, "faults": 0}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Tuner":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pathway-tuner", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + 5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    @property
    def frozen(self) -> bool:
        return self._frozen

    # -- the control loop ----------------------------------------------------
    def tick(self) -> int:
        """One pass over every controller; returns adjustments applied.
        Never raises: an injected/internal fault reverts all tuner state
        and freezes the loop (static config is the fallback plan)."""
        if self._frozen:
            return 0
        self.stats["ticks"] += 1
        try:
            inject.fire("tuner.adjust")
            n = 0
            n += self._tune_coalesce()
            n += self._tune_step_bucket()
            n += self._tune_cache_budgets()
            n += self._tune_profile_sample()
            return n
        except Exception:
            self.stats["faults"] += 1
            observe.count("pathway_tuner_faults_total")
            self.revert()
            self._frozen = True
            return 0

    def propose(self, knob: str, value: Any, direction: str) -> bool:
        """Route one adjustment through the registry: clamp, journal,
        count.  A ``static`` knob is vetoed (counted, False).  This is
        the ONLY write path controllers use."""
        try:
            before = config.overrides().get(knob)
            had = knob in config.overrides()
            applied = config.set(knob, value)
        except StaticKnobError:
            self.stats["vetoes"] += 1
            observe.count("pathway_tuner_vetoed_total", knob=knob)
            return False
        with self._lock:
            if knob not in self._journaled:
                self._journaled.add(knob)
                self._journal.append((knob, had, before))
        self.stats["adjustments"] += 1
        observe.count(
            "pathway_tuner_adjustments_total", knob=knob, direction=direction
        )
        observe.gauge("pathway_tuner_value", knob=knob).set(float(applied))
        return True

    def revert(self) -> None:
        """Restore the pre-tuner world: unwind every journaled override
        (newest-first) and re-point live tier budgets at their original
        ``max_bytes``."""
        with self._lock:
            journal = list(reversed(self._journal))
            self._journal.clear()
            self._journaled.clear()
            tier_bytes0 = dict(self._tier_bytes0)
            self._tier_bytes0.clear()
        for knob, had, before in journal:
            if had:
                try:
                    config.set(knob, before)
                except StaticKnobError:  # pragma: no cover - journal is dynamic-only
                    pass
            else:
                config.clear_override(knob)
        for ref, max_bytes in tier_bytes0.values():
            tier = ref()
            if tier is not None:
                tier.max_bytes = max_bytes

    # -- signals -------------------------------------------------------------
    def _delta(self, key: str, current: float) -> float:
        prev = self._last.get(key, 0.0)
        self._last[key] = current
        return current - prev

    def _queue_wait_mean_s(self) -> Optional[float]:
        """Mean serve queue wait over the last tick window (histogram
        delta), or None when no requests landed."""
        h = observe.histogram("pathway_serve_queue_wait_seconds")
        _, sum_ns, n = h.snapshot()
        d_sum = self._delta("qw_sum_ns", float(sum_ns))
        d_n = self._delta("qw_n", float(n))
        if d_n <= 0:
            return None
        return (d_sum / d_n) * 1e-9

    def _slo_fast_burn(self) -> float:
        """Worst fast-window burn rate across latency SLOs (0 = all
        headroom, >= 1 = budget burning faster than allotted)."""
        try:
            from ..observe import slo

            report = slo.evaluate()
        except Exception:
            return 0.0
        worst = 0.0
        for row in (report.get("slos") or {}).values():
            fast = (row.get("windows") or {}).get("fast") or {}
            if fast.get("events"):
                worst = max(worst, float(fast.get("burn_rate") or 0.0))
        return worst

    def _occupancy(self, site: str) -> Optional[float]:
        """real/padded pack-row ratio for ``site`` over the last tick."""
        real = observe.counter(
            "pathway_serve_pack_rows_total", site=site, kind="real"
        ).value
        padded = observe.counter(
            "pathway_serve_pack_rows_total", site=site, kind="padded"
        ).value
        d_real = self._delta(f"occ_real_{site}", float(real))
        d_padded = self._delta(f"occ_padded_{site}", float(padded))
        if d_padded <= 0:
            return None
        return d_real / d_padded

    # -- controllers ---------------------------------------------------------
    def _tune_coalesce(self) -> int:
        window_us = float(config.get("serve.coalesce_us"))
        mean_wait = self._queue_wait_mean_s()
        burn = self._slo_fast_burn()
        if burn >= 1.0:
            # latency budget burning: the window is the one knob that
            # trades batching for immediate latency — shrink it, floored
            # at 50us (below that coalescing is already off in practice;
            # decaying toward 0 would just journal no-op adjustments)
            if window_us <= 50.0:
                return 0
            return int(
                self.propose(
                    "serve.coalesce_us", max(window_us * 0.7, 50.0), "down"
                )
            )
        if (
            mean_wait is not None
            and burn < 0.5
            and window_us > 0
            and mean_wait * 1e6 >= 0.5 * window_us
        ):
            # headroom ample and the window itself is the binding wait:
            # grow it for denser batches
            return int(
                self.propose(
                    "serve.coalesce_us", max(window_us * 1.3, 100.0), "up"
                )
            )
        return 0

    def _tune_step_bucket(self) -> int:
        occ = self._occupancy("generator")
        if occ is None:
            return 0
        bucket = int(config.get("decode.step_bucket"))
        if occ < _OCC_LOW and bucket > 1:
            return int(
                self.propose("decode.step_bucket", bucket // 2, "down")
            )
        if occ > _OCC_HIGH:
            return int(self.propose("decode.step_bucket", bucket * 2, "up"))
        return 0

    def _tune_cache_budgets(self) -> int:
        import weakref

        from ..cache.store import live_tiers

        n = 0
        for tier in live_tiers():
            knob = _TIER_KNOB.get(tier.tier)
            if knob is None:
                continue
            tag = f"tier_{tier.labels.get('id', tier.tier)}"
            d_hits = self._delta(f"{tag}_hits", float(tier.stats["hits"]))
            d_evict = self._delta(
                f"{tag}_evict", float(tier.stats["evictions"])
            )
            d_miss = self._delta(f"{tag}_miss", float(tier.stats["misses"]))
            budget = int(config.get(knob))
            direction = None
            if d_evict > 0 and d_hits > 0:
                # evicting while hits still climb: every evicted entry
                # was a future hit — the budget is the binding resource
                direction, factor = "up", _GROW
            elif (
                d_hits <= 0
                and d_miss <= 0
                and tier.bytes < budget // 2
                and budget > 1 << 20
            ):
                # idle tier holding a large budget: give the HBM back
                direction, factor = "down", _SHRINK
            if direction is None:
                continue
            if self.propose(knob, int(budget * factor), direction):
                key = id(tier)
                if key not in self._tier_bytes0:
                    self._tier_bytes0[key] = (
                        weakref.ref(tier),
                        tier.max_bytes,
                    )
                tier.max_bytes = int(config.get(knob))
                n += 1
        return n

    def _tune_profile_sample(self) -> int:
        from ..observe import profile

        samples = 0.0
        for row in profile.profile_stats().values():
            samples += float(row.get("samples", 0))
        d_samples = self._delta("profile_samples", samples)
        wall_s = max(self.interval_s, 1e-3)
        overhead = (d_samples * _PROFILE_SAMPLE_COST_S) / wall_s
        fraction = float(config.get("observe.profile_sample"))
        if overhead > _PROFILE_OVERHEAD_HIGH and fraction > 0.0:
            if self.propose(
                "observe.profile_sample", fraction * 0.5, "down"
            ):
                profile.set_sample(config.get("observe.profile_sample"))
                return 1
        elif (
            0.0 < overhead < _PROFILE_OVERHEAD_LOW
            and d_samples > 0
            and fraction < 1.0
        ):
            if self.propose(
                "observe.profile_sample", min(fraction * 2.0, 1.0), "up"
            ):
                profile.set_sample(config.get("observe.profile_sample"))
                return 1
        return 0


def tuner_from_env() -> Optional[Tuner]:
    """Start a background tuner when ``PATHWAY_TUNER=1``; the interval
    comes from ``PATHWAY_TUNER_INTERVAL_S``.  Returns the running tuner
    or None (the default: static config, no background thread)."""
    if not config.get("tuner.enabled"):
        return None
    return Tuner().start()
