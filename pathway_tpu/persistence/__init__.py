"""Persistence configuration (checkpoint/resume).

Reference surface: python/pathway/persistence/__init__.py:13-88 (Backend /
Config classes) over src/persistence/ (input snapshots + operator snapshots
through pluggable backends).  The engine-side snapshot/restore implementation
lives in pathway_tpu/persistence/engine_state.py.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["Backend", "Config", "PersistenceMode", "SnapshotAccess"]


class PersistenceMode(enum.Enum):
    """(reference: engine.pyi:777-787)"""

    BATCH = "batch"
    PERSISTING = "persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    SPEEDRUN_REPLAY = "speedrun_replay"
    REALTIME_REPLAY = "realtime_replay"


class SnapshotAccess(enum.Enum):
    RECORD = "record"
    REPLAY = "replay"
    FULL = "full"
    OFFSETS_ONLY = "offsets_only"


@dataclass
class Backend:
    """Storage backend for snapshots (reference: persistence/__init__.py:13)."""

    kind: str
    path: Optional[str] = None
    bucket: Optional[str] = None

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(kind="filesystem", path=path)

    @classmethod
    def s3(cls, root_path: str, bucket_settings=None) -> "Backend":
        return cls(kind="s3", path=root_path)

    @classmethod
    def mock(cls) -> "Backend":
        return cls(kind="mock")

    def make_store(self):
        from .backends import FileBackend, MemoryBackend

        if self.kind == "filesystem":
            return FileBackend(self.path)
        if self.kind == "mock":
            return MemoryBackend()
        if self.kind == "s3":
            raise NotImplementedError(
                "S3 persistence backend requires an S3 client; mount the bucket "
                "and use Backend.filesystem instead"
            )
        raise ValueError(self.kind)


@dataclass
class Config:
    """(reference: persistence/__init__.py:88 Config.simple_config)"""

    backend: Optional[Backend] = None
    snapshot_interval_ms: int = 60000
    persistence_mode: PersistenceMode = PersistenceMode.PERSISTING
    snapshot_access: SnapshotAccess = SnapshotAccess.FULL
    continue_after_replay: bool = True

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend=backend, **kwargs)
