"""Persistence configuration (checkpoint/resume).

Reference surface: python/pathway/persistence/__init__.py:13-88 (Backend /
Config classes) over src/persistence/ (input snapshots + operator snapshots
through pluggable backends).  The engine-side snapshot/restore implementation
lives in pathway_tpu/persistence/engine_state.py.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from typing import Optional

from .object_cache import CachedObjectStorage

__all__ = [
    "Backend",
    "Config",
    "PersistenceMode",
    "SnapshotAccess",
    "CachedObjectStorage",
]


class PersistenceMode(enum.Enum):
    """(reference: engine.pyi:777-787)"""

    BATCH = "batch"
    PERSISTING = "persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    SPEEDRUN_REPLAY = "speedrun_replay"
    REALTIME_REPLAY = "realtime_replay"


class SnapshotAccess(enum.Enum):
    RECORD = "record"
    REPLAY = "replay"
    FULL = "full"
    OFFSETS_ONLY = "offsets_only"


@dataclass
class Backend:
    """Storage backend for snapshots (reference: persistence/__init__.py:13)."""

    kind: str
    path: Optional[str] = None
    bucket: Optional[str] = None

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(kind="filesystem", path=path)

    @classmethod
    def s3(cls, root_path: str, bucket_settings=None) -> "Backend":
        return cls(kind="s3", path=root_path)

    @classmethod
    def mock(cls) -> "Backend":
        return cls(kind="mock")

    _mock_instance = None

    def make_store(self):
        from .backends import FileBackend, MemoryBackend, S3Backend

        if self.kind == "filesystem":
            return FileBackend(self.path)
        if self.kind == "mock":
            # one shared in-memory store per Backend object, so successive
            # runs against the same Backend see earlier snapshots (tests)
            if self._mock_instance is None:
                self._mock_instance = MemoryBackend()
            return self._mock_instance
        if self.kind == "s3":
            return S3Backend(bucket=self.bucket or "", root_path=self.path or "")
        raise ValueError(self.kind)


@dataclass
class Config:
    """(reference: persistence/__init__.py:88 Config.simple_config)"""

    backend: Optional[Backend] = None
    snapshot_interval_ms: int = 60000
    persistence_mode: PersistenceMode = PersistenceMode.PERSISTING
    snapshot_access: SnapshotAccess = SnapshotAccess.FULL
    continue_after_replay: bool = True

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend=backend, **kwargs)
