"""CRC-framed record encoding for snapshot chunks.

Frame = ``[u32 LE payload_len][u32 LE crc32(payload)][payload]`` — the framing
under the input-snapshot event log (reference analog: chunked snapshot events
in src/persistence/input_snapshot.rs).  A torn write (process killed mid-put)
or bit rot is detected on replay: ``scan`` returns only the valid prefix, so
recovery rewinds to the last intact record instead of failing the run.
Scanning is done by the native library (native/src/snapshot.cc) when present.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

from .. import native

__all__ = ["frame", "scan"]


def frame(payload: bytes) -> bytes:
    return struct.pack("<II", len(payload), native.crc32(payload)) + payload


def scan(blob: bytes) -> Tuple[List[bytes], bool]:
    """Decode concatenated frames; returns (payloads, intact) where intact is
    False if a truncated/corrupt tail was dropped."""
    offs, lens, consumed = native.frame_scan(blob)
    payloads = [bytes(blob[o : o + l]) for o, l in zip(offs, lens)]
    return payloads, consumed == len(blob)
