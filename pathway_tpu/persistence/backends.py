"""Snapshot storage backends — KV blob stores.

Reference: trait PersistenceBackend (src/persistence/backends/mod.rs:50) with
file / S3 / memory / mock implementations.  Keys are slash-separated paths;
values are opaque byte blobs.  Writes are atomic (temp file + rename on the
filesystem backend) so a crash mid-snapshot never corrupts an earlier one.

The S3 backend's ``get``/``put``/``list_keys`` run through
``robust.retry_call`` (sites ``s3.get`` / ``s3.put`` / ``s3.list``) —
a transient socket error inside a warm-state snapshot write retries
with the standard seeded-jitter backoff and counts on
``pathway_robust_retries_total{site}`` instead of propagating raw out
of the snapshot path.  ``delete`` stays single-shot: it is only called
from best-effort pruning, where a miss is already tolerated.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from ..robust import retry_call

__all__ = ["PersistenceBackend", "FileBackend", "MemoryBackend", "S3Backend"]


class PersistenceBackend:
    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError


class FileBackend(PersistenceBackend):
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.normpath(os.path.join(self.root, key))
        if path != self.root and not path.startswith(self.root + os.sep):
            raise ValueError(f"key escapes storage root: {key!r}")
        return path

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list_keys(self, prefix: str = "") -> List[str]:
        out = []
        for root, _dirs, files in os.walk(self.root):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(root, f), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


class MemoryBackend(PersistenceBackend):
    """In-memory store (reference mock.rs) — shared when the same instance is
    passed to successive runs; used by tests."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def list_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))


class S3Backend(PersistenceBackend):
    """S3 KV backend (reference backends/s3.rs), gated on boto3."""

    def __init__(self, bucket: str, root_path: str = "", client=None):
        if client is None:
            try:
                import boto3  # type: ignore
            except ImportError as e:  # pragma: no cover
                raise ImportError(
                    "S3 persistence requires boto3 (not installed); pass a "
                    "client explicitly or use Backend.filesystem"
                ) from e
            client = boto3.client("s3")
        self.client = client
        self.bucket = bucket
        self.root = root_path.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.root}/{key}" if self.root else key

    def get(self, key: str) -> Optional[bytes]:
        return retry_call("s3.get", self._get_once, key)

    def _get_once(self, key: str) -> Optional[bytes]:
        try:
            obj = self.client.get_object(Bucket=self.bucket, Key=self._key(key))
            return obj["Body"].read()
        except self.client.exceptions.NoSuchKey:
            return None

    def put(self, key: str, value: bytes) -> None:
        retry_call(
            "s3.put",
            self.client.put_object,
            Bucket=self.bucket,
            Key=self._key(key),
            Body=value,
        )

    def delete(self, key: str) -> None:
        self.client.delete_object(Bucket=self.bucket, Key=self._key(key))

    def list_keys(self, prefix: str = "") -> List[str]:
        return retry_call("s3.list", self._list_once, prefix)

    def _list_once(self, prefix: str) -> List[str]:
        full = self._key(prefix)
        out = []
        paginator = self.client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=full):
            for item in page.get("Contents", []):
                key = item["Key"]
                if self.root:
                    key = key[len(self.root) + 1 :]
                out.append(key)
        return sorted(out)
