"""Snapshot record/replay engine state.

Reference model (src/persistence/): two snapshot kinds through a pluggable
backend —
  * input snapshots: raw connector events + source offsets per persistent_id
    (input_snapshot.rs; connectors replay the log then *seek* the source past
    already-ingested data);
  * operator snapshots: stateful-operator state at a committed frontier
    (operator_snapshot.rs), enabled by PersistenceMode.OPERATOR_PERSISTING.

Chunk layout under the backend:
  sources/{pid}/chunk-{seq:08d}   pickled list of raw session events
  sources/{pid}/METADATA          {"chunks": n, "offsets": obj, "frontier": ts}
  operators/{stable_id}           pickled operator state at last commit
  COMMIT                          {"frontier": ts, "ops": bool} — written last
"""

from __future__ import annotations

import logging
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import Config, PersistenceMode, SnapshotAccess
from .backends import PersistenceBackend
from .framing import frame, scan

logger = logging.getLogger(__name__)

__all__ = ["SourcePersistence", "PersistenceManager"]

Event = Tuple[int, int, Optional[tuple]]


class SourcePersistence:
    """Per-connector recorder + restored state handed to the connector runner
    (via ``SessionWriter.persistence``)."""

    def __init__(
        self,
        backend: PersistenceBackend,
        persistent_id: str,
        record: bool = True,
    ):
        self.backend = backend
        self.pid = persistent_id
        self.record_enabled = record
        self._lock = threading.Lock()
        self._buffer: List[Event] = []
        self._offsets: Any = None
        meta = backend.get(f"sources/{self.pid}/METADATA")
        self._meta = pickle.loads(meta) if meta else {"chunks": 0, "offsets": None}
        self._offsets = self._meta.get("offsets")

    # -- runner-facing API -------------------------------------------------
    def offsets(self) -> Any:
        """Last committed source position (connector-defined shape)."""
        return self._offsets

    def save_offsets(self, offsets: Any) -> None:
        with self._lock:
            self._offsets = offsets

    # -- engine-facing API -------------------------------------------------
    def record(self, event: Event) -> None:
        if not self.record_enabled:
            return
        with self._lock:
            self._buffer.append(event)

    #: chunk format marker; bump when the framing/payload encoding changes so
    #: old snapshots are recognized instead of being misread as corruption
    CHUNK_MAGIC = b"PWC1"

    def replay_events(self) -> List[Event]:
        """Replay recorded events; each chunk is a CRC-framed record log, so a
        torn/corrupt tail truncates replay at the last intact record rather
        than failing (the reference's rewind-to-common-frontier behavior,
        docs/.../10.worker-architecture.md:58-61).

        On a corrupt tail the log is REWRITTEN at the truncation point: the
        torn chunk is replaced by its intact prefix and later chunks are
        deleted, so subsequent flushes append consistently — otherwise every
        future replay would re-hit the torn chunk and silently drop
        everything recorded after the first recovery."""
        events: List[Event] = []
        n_chunks = self._meta.get("chunks", 0)
        for seq in range(n_chunks):
            key = f"sources/{self.pid}/chunk-{seq:08d}"
            blob = self.backend.get(key)
            if not blob:
                continue
            if blob.startswith(self.CHUNK_MAGIC):
                blob = blob[len(self.CHUNK_MAGIC):]
            payloads, intact = scan(blob)
            for p in payloads:
                events.append(pickle.loads(p))
            if not intact:
                logger.warning(
                    "snapshot chunk %s/%08d has a corrupt tail; replay "
                    "truncated at the last intact record%s",
                    self.pid,
                    seq,
                    " and the log rewound to this point"
                    if self.record_enabled
                    else "",
                )
                if self.record_enabled:
                    # about to append new events: rewind the on-disk log so
                    # future flushes stay reachable.  In replay-only mode
                    # (SnapshotAccess.REPLAY) never mutate the backend —
                    # truncation is in-memory and the data stays recoverable.
                    self._truncate_log_at(seq, payloads)
                break
        return events

    def _truncate_log_at(self, seq: int, intact_payloads: List[bytes]) -> None:
        """Rewrite chunk ``seq`` with its intact prefix, drop later chunks,
        rewind the chunk counter AND the saved source offsets so the next run
        re-reads from before the tear (at-least-once: later chunks' events
        come back from the source instead of being lost — the committed
        frontier/offsets would otherwise seek past data that no longer
        exists on disk)."""
        key = f"sources/{self.pid}/chunk-{seq:08d}"
        if intact_payloads:
            self.backend.put(
                key,
                self.CHUNK_MAGIC + b"".join(frame(p) for p in intact_payloads),
            )
            self._meta["chunks"] = seq + 1
        else:
            self.backend.delete(key)
            self._meta["chunks"] = seq
        # sweep every chunk file at/after the new counter (incl. torn runs)
        for k in self.backend.list_keys(f"sources/{self.pid}/"):
            name = k.rsplit("/", 1)[-1]
            if name.startswith("chunk-"):
                try:
                    s = int(name[len("chunk-"):])
                except ValueError:
                    continue
                if s >= self._meta["chunks"]:
                    self.backend.delete(f"sources/{self.pid}/chunk-{s:08d}")
        # rewind offsets to the snapshot taken at the last surviving chunk;
        # keyed by seq (not list position) so truncation never desynchronizes
        # the mapping.  chunk seq's own snapshot also covers its lost tail,
        # so the chunk BEFORE the tear is the newest trustworthy position;
        # a missing entry (legacy metadata) degrades to None = re-read all.
        chunk_offsets = dict(self._meta.get("chunk_offsets") or {})
        rewind_to = seq - 1
        self._offsets = chunk_offsets.get(rewind_to)
        self._meta["offsets"] = self._offsets
        self._meta["chunk_offsets"] = {
            s: o for s, o in chunk_offsets.items() if s <= rewind_to
        }
        self._meta["sealed"] = min(
            self._meta.get("sealed", 0), self._meta["chunks"]
        )
        self.backend.put(f"sources/{self.pid}/METADATA", pickle.dumps(self._meta))

    #: merge the chunk log once it exceeds this many files (reference:
    #: ConcreteSnapshotMerger background compaction, operator_snapshot.rs:337)
    COMPACT_AFTER = 64

    def flush(self, frontier: int) -> None:
        with self._lock:
            buffer, self._buffer = self._buffer, []
            offsets = self._offsets
        if buffer:
            seq = self._meta["chunks"]
            chunk = self.CHUNK_MAGIC + b"".join(
                frame(pickle.dumps(event)) for event in buffer
            )
            self.backend.put(f"sources/{self.pid}/chunk-{seq:08d}", chunk)
            self._meta["chunks"] = seq + 1
            # per-chunk offsets snapshot (keyed by seq): lets corrupt-tail
            # recovery rewind the source position together with the log
            chunk_offsets = self._meta.get("chunk_offsets")
            if not isinstance(chunk_offsets, dict):
                chunk_offsets = {}
                self._meta["chunk_offsets"] = chunk_offsets
            chunk_offsets[seq] = offsets
            if (
                self._meta["chunks"] - self._meta.get("sealed", 0)
                > self.COMPACT_AFTER
            ):
                self._compact()
        self._meta["offsets"] = offsets
        self._meta["frontier"] = frontier
        self.backend.put(f"sources/{self.pid}/METADATA", pickle.dumps(self._meta))

    def _merge_range(self, start: int, end: int) -> None:
        """Merge chunks [start, end) into one chunk at ``start``."""
        merged: List[bytes] = []
        last_intact = start - 1
        for seq in range(start, end):
            key = f"sources/{self.pid}/chunk-{seq:08d}"
            blob = self.backend.get(key)
            if not blob:
                continue
            if blob.startswith(self.CHUNK_MAGIC):
                blob = blob[len(self.CHUNK_MAGIC):]
            payloads, intact = scan(blob)
            merged.extend(payloads)
            last_intact = seq
            if not intact:
                break
        self.backend.put(
            f"sources/{self.pid}/chunk-{start:08d}",
            self.CHUNK_MAGIC + b"".join(frame(p) for p in merged),
        )
        for seq in range(start + 1, end):
            self.backend.delete(f"sources/{self.pid}/chunk-{seq:08d}")
        chunk_offsets = dict(self._meta.get("chunk_offsets") or {})
        kept = {s: o for s, o in chunk_offsets.items() if s < start}
        kept[start] = chunk_offsets.get(last_intact)
        self._meta["chunks"] = start + 1
        self._meta["chunk_offsets"] = kept

    def _compact(self) -> None:
        """Tiered merge: seal the newest COMPACT_AFTER chunks into one
        segment; when sealed segments pile up, merge them too.  Each event is
        rewritten O(1) times per tier (amortized O(n log n) backend I/O over
        a job's lifetime — a full-log rewrite every 64 flushes would be
        quadratic).  File count stays <= 2*COMPACT_AFTER; byte growth is
        inherent to an input log (OPERATOR_PERSISTING truncates bytes via
        drop_log)."""
        sealed = self._meta.get("sealed", 0)  # chunks below this are sealed
        self._merge_range(sealed, self._meta["chunks"])
        self._meta["sealed"] = sealed + 1
        if self._meta["sealed"] > self.COMPACT_AFTER:
            self._merge_range(0, self._meta["chunks"])
            self._meta["sealed"] = 1

    def drop_log(self) -> None:
        """Delete every recorded chunk (OPERATOR_PERSISTING: once operator
        snapshots cover the frontier, the input log before it is dead
        weight — restores come from operator state, not replay)."""
        for seq in range(self._meta["chunks"]):
            self.backend.delete(f"sources/{self.pid}/chunk-{seq:08d}")
        self._meta["chunks"] = 0
        self._meta["sealed"] = 0
        self._meta["chunk_offsets"] = {}
        self.backend.put(f"sources/{self.pid}/METADATA", pickle.dumps(self._meta))


# Operator/table snapshots embed derived row keys (join output keys, group
# keys); bump this whenever key derivation changes so stale snapshots are
# rejected loudly and the run falls back to input-event replay (which
# re-derives every key) instead of silently mixing key formats.
SNAPSHOT_FORMAT = 2


class PersistenceManager:
    """Wires a Config into a built engine graph: replays input snapshots
    before the run, records new events, and (in OPERATOR_PERSISTING mode)
    checkpoints/restores stateful-operator state."""

    def __init__(self, config: Config):
        if config.backend is None:
            raise ValueError("persistence Config.backend is required")
        self.config = config
        self.backend: PersistenceBackend = config.backend.make_store()
        self.interval_ms = max(int(config.snapshot_interval_ms), 1)
        self._sources: List[Tuple[Any, SourcePersistence]] = []
        self._graph = None
        self._last_flush_ts = 0
        commit = self.backend.get("COMMIT")
        self._commit = pickle.loads(commit) if commit else None

    @property
    def operator_mode(self) -> bool:
        return self.config.persistence_mode == PersistenceMode.OPERATOR_PERSISTING

    # -- wiring ------------------------------------------------------------
    def attach(self, graph) -> None:
        """Replay snapshots into source sessions and start recording.
        Must run after graph build, before connector hooks start."""
        self._graph = graph
        access = self.config.snapshot_access
        record = access in (SnapshotAccess.RECORD, SnapshotAccess.FULL)
        replay = access in (SnapshotAccess.REPLAY, SnapshotAccess.FULL)
        restored_ops = self.operator_mode and self._restore_operators()
        # operator-mode commits truncate the input log (drop_log); if the
        # operator snapshot then can't be used (format bump, mode switched
        # back to input replay), the only safe recovery is a FULL re-ingest:
        # reset source offsets so connectors re-read from the beginning
        # instead of seeking past data whose log no longer exists
        logs_dropped = bool(self._commit and self._commit.get("ops"))
        reset_offsets = logs_dropped and not restored_ops
        if reset_offsets:
            import logging

            logging.getLogger(__name__).warning(
                "operator snapshots unusable but the input log was truncated "
                "by OPERATOR_PERSISTING commits — resetting source offsets "
                "for a full re-ingest (at-least-once recovery)"
            )
        for src in graph.sources:
            pid = getattr(src, "persistent_id", None)
            writer = getattr(src, "writer", None)
            if not pid:
                continue
            sp = SourcePersistence(self.backend, pid, record=record)
            if reset_offsets:
                sp.save_offsets(None)
            if writer is not None:
                writer.persistence = sp
            if record:
                src.session.recorder = sp.record
            if replay and not restored_ops:
                events = sp.replay_events()
                if events:
                    src.session.push_raw(events)
            self._sources.append((src, sp))

    def _stable_ids(self):
        """Deterministic operator keys: construction order + class name (the
        same user script rebuilds the same graph in the same order)."""
        out = []
        for i, op in enumerate(self._graph.operators):
            out.append((f"{i:05d}-{type(op).__name__}", op))
        return out

    def _restore_operators(self) -> bool:
        if not self._commit or not self._commit.get("ops"):
            return False
        if self._commit.get("format") != SNAPSHOT_FORMAT:
            import logging

            logging.getLogger(__name__).warning(
                "operator snapshot format %s != current %s; ignoring operator "
                "snapshots and replaying input events instead",
                self._commit.get("format"),
                SNAPSHOT_FORMAT,
            )
            return False
        restored = 0
        for stable_id, op in self._stable_ids():
            blob = self.backend.get(f"operators/{stable_id}")
            if blob is None:
                continue
            state = pickle.loads(blob)
            try:
                op.restore_state(state)
                restored += 1
            except NotImplementedError:
                pass
        # table row stores (retraction-lookup state — the analog of restored
        # differential arrangements)
        for i, table in enumerate(self._graph.tables):
            blob = self.backend.get(f"tables/{i:05d}")
            if blob is not None:
                table.store._rows = pickle.loads(blob)
                restored += 1
        return restored > 0

    def _snapshot_operators(self) -> bool:
        any_saved = False
        for stable_id, op in self._stable_ids():
            try:
                state = op.snapshot_state()
            except NotImplementedError:
                continue
            if state is None:
                continue
            self.backend.put(f"operators/{stable_id}", pickle.dumps(state))
            any_saved = True
        if any_saved:
            for i, table in enumerate(self._graph.tables):
                self.backend.put(f"tables/{i:05d}", pickle.dumps(table.store._rows))
        return any_saved

    # -- runtime -----------------------------------------------------------
    def on_tick(self, ts: int) -> None:
        if ts - self._last_flush_ts >= self.interval_ms:
            self.commit(ts)

    def commit(self, ts: int) -> None:
        self._last_flush_ts = ts
        for _src, sp in self._sources:
            sp.flush(ts)
        ops_saved = self.operator_mode and self._snapshot_operators()
        self.backend.put(
            "COMMIT",
            pickle.dumps(
                {
                    "frontier": ts,
                    "ops": bool(ops_saved),
                    "format": SNAPSHOT_FORMAT,
                }
            ),
        )
        if ops_saved:
            # the operator snapshot covers everything flushed above; the
            # input log is no longer needed for recovery (this is what keeps
            # OPERATOR_PERSISTING byte-bounded on long-running jobs)
            for _src, sp in self._sources:
                sp.drop_log()

    def finalize(self, ts: int) -> None:
        self.commit(ts)
