"""Cached object storage — blob cache for re-computable artifacts.

The reference caches re-downloadable objects (fetched files, parse results)
in persistent storage so restarts skip the re-download/re-parse
(src/persistence/cached_object_storage.rs:377).  Here the cache is a thin
keyed-blob layer over any ``PersistenceBackend`` (file/S3/memory) with
version-aware keys: ``get_or_compute`` recomputes only when the (key,
version) pair is unseen — e.g. a document parser keyed by (path, mtime)
re-parses a file only when it actually changed across restarts.
"""

from __future__ import annotations

import hashlib
import pickle
import threading
from typing import Any, Callable, Optional

from .backends import PersistenceBackend

__all__ = ["CachedObjectStorage"]


def _digest(key: Any, version: Any) -> str:
    raw = pickle.dumps((key, version))
    return hashlib.sha256(raw).hexdigest()


class CachedObjectStorage:
    def __init__(self, backend: PersistenceBackend, namespace: str = "objects"):
        self.backend = backend
        self.namespace = namespace
        self._lock = threading.Lock()
        # in-flight computes keyed by blob key: dedups same-key work
        # without holding any lock across compute()/pickle (see
        # get_or_compute)
        self._inflight: dict = {}

    def _blob_key(self, key: Any, version: Any) -> str:
        return f"{self.namespace}/{_digest(key, version)}"

    def get(self, key: Any, version: Any = None) -> Optional[Any]:
        blob = self.backend.get(self._blob_key(key, version))
        return pickle.loads(blob) if blob is not None else None

    def contains(self, key: Any, version: Any = None) -> bool:
        return self.backend.get(self._blob_key(key, version)) is not None

    def put(self, key: Any, value: Any, version: Any = None) -> None:
        self.backend.put(self._blob_key(key, version), pickle.dumps(value))

    def invalidate(self, key: Any, version: Any = None) -> None:
        self.backend.delete(self._blob_key(key, version))

    def clear(self) -> None:
        for k in self.backend.list_keys(f"{self.namespace}/"):
            self.backend.delete(k)

    def get_or_compute(
        self, key: Any, compute: Callable[[], Any], version: Any = None
    ) -> Any:
        """Cached call: returns the stored value for (key, version), or runs
        ``compute`` once and stores its result.  Backends are
        last-writer-wins like the reference.

        Same-key in-process races dedup through a per-key in-flight event
        instead of one global critical section: the old structure held the
        cache-wide lock across ``compute()`` (arbitrary user code — a PDF
        parse, a model call) AND the pickle of its result (one GIL-holding
        C call for the whole payload), so every other thread's cache access
        stalled behind it — the round-5 ``parallel/exchange.py`` bug class,
        flagged by the lock-discipline lint.  The global lock now only
        guards the in-flight dict (a couple of dict ops)."""
        bkey = self._blob_key(key, version)
        blob = self.backend.get(bkey)
        if blob is not None:
            return pickle.loads(blob)
        with self._lock:
            waiter = self._inflight.get(bkey)
            event = None
            if waiter is None:
                event = self._inflight[bkey] = threading.Event()
        if waiter is not None:
            # another thread owns this key's compute: wait, then re-read
            waiter.wait()
            blob = self.backend.get(bkey)
            if blob is not None:
                return pickle.loads(blob)
            # the owner failed; claim ownership for our own attempt (if a
            # third thread already re-claimed it, compute un-deduped —
            # correctness over dedup, and never wait twice)
            with self._lock:
                if self._inflight.get(bkey) is None:
                    event = self._inflight[bkey] = threading.Event()
        try:
            blob = self.backend.get(bkey)
            if blob is not None:
                return pickle.loads(blob)
            value = compute()
            self.backend.put(bkey, pickle.dumps(value))
            return value
        finally:
            # only the OWNER retires its own event: popping someone
            # else's entry would wake their waiters before the value lands
            if event is not None:
                with self._lock:
                    if self._inflight.get(bkey) is event:
                        del self._inflight[bkey]
                event.set()
