"""The engine run loop.

The reference's per-worker hot loop is ``probers → flushers → pollers →
worker.step_or_park`` (src/engine/dataflow.rs:5596-5650).  Here one host
drives the whole graph: each iteration polls every source session, stamps a
new commit tick (even unix-ms, matching the reference's alt-neu even-time
convention, src/engine/time.rs:22-28), propagates the resulting deltas in
topological order, and fires tick-end hooks.  In batch mode (all sources
static/finished) the loop drains and returns; in streaming mode it parks for
``commit_duration`` between ticks until terminated.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, List, Optional, Tuple

from .delta import Delta
from .graph import EngineGraph, EngineOperator
from .operators.io import SourceOperator
from .operators.io import _COLUMNAR

__all__ = ["Executor", "Timestamp", "next_timestamp"]

Timestamp = int

_last_ts_lock = threading.Lock()
_last_ts = 0


def next_timestamp() -> Timestamp:
    """Monotone even-millisecond timestamps (reference Timestamp::new_from_current_time,
    src/engine/time.rs:20-28)."""
    global _last_ts
    with _last_ts_lock:
        ts = int(_time.time() * 1000)
        ts += ts % 2  # round up to even
        if ts <= _last_ts:
            ts = _last_ts + 2
        _last_ts = ts
        return ts


def bump_timestamp(ts: Timestamp) -> None:
    """Advance the local clock to an externally-agreed commit timestamp
    (distributed runs: the cluster's tick timestamp is the max over all
    ranks' proposals, so each rank's later local timestamps stay above it)."""
    global _last_ts
    with _last_ts_lock:
        if ts > _last_ts:
            _last_ts = ts


class Executor:
    def __init__(
        self,
        graph: EngineGraph,
        commit_duration_ms: int = 100,
        on_tick: Optional[Callable[[Timestamp], None]] = None,
    ):
        self.graph = graph
        self.commit_duration_ms = commit_duration_ms
        self.on_tick = on_tick
        self._terminate = threading.Event()
        self.current_ts: Timestamp = 0
        self._ctrl_seq = 0  # distributed control-plane BSP round counter

    def terminate(self) -> None:
        self._terminate.set()

    def step(self, ts: Optional[Timestamp] = None) -> bool:
        """Poll all sources once and propagate; returns True if any data moved."""
        ts = ts if ts is not None else next_timestamp()
        self.current_ts = ts
        initial: List[Tuple[EngineOperator, int, Delta]] = []
        for src in self.graph.sources:
            delta = src.poll(ts)
            if delta is not None and delta.n > 0:
                delta = delta.consolidated()
                src.output.store.apply(delta)
                for consumer, port in src.output.consumers:
                    initial.append((consumer, port, delta))
        moved = bool(initial)
        if initial:
            self.graph.propagate(initial, ts)
        self.graph.tick_end(ts)
        if self.on_tick is not None:
            self.on_tick(ts)
        return moved

    def run(self, bootstrap=None) -> None:
        """Run until all sources are finished (and drained) or terminated.

        ``bootstrap``: (operator, port, delta) triples to inject at the first
        tick (used by incremental re-runs for operators added after a
        previous run)."""
        plane = None
        from ..parallel import distributed

        if distributed.is_distributed():
            from ..parallel.exchange import get_plane

            plane = get_plane()
            self.graph.plane = plane
        self.graph.finalize()
        if bootstrap:
            ts = next_timestamp()
            self.current_ts = ts
            self.graph.propagate(list(bootstrap), ts)
        while True:
            if plane is not None:
                # termination is part of the tick protocol: a local
                # terminate() request only takes effect once every rank has
                # seen it in the status exchange, so no rank blocks in a
                # collective against an exited peer
                moved, finished, stop = self._step_dist(plane)
                if stop:
                    break
            else:
                if self._terminate.is_set():
                    break
                moved = self.step()
                finished = self._sources_finished()
            if finished and not moved:
                # final flush for buffered/time-based operators
                if plane is not None:
                    ts = self.current_ts + 2  # agreed: same current_ts on all ranks
                    bump_timestamp(ts)
                else:
                    ts = next_timestamp()
                self.current_ts = ts
                self.graph.flush_end(ts)
                break
            if not moved:
                self._terminate.wait(self.commit_duration_ms / 1000.0)

    def _sources_finished(self) -> bool:
        """Batch-run completion: every source is finished, where a
        loop-back source (AsyncTransformer results) counts as finished when
        QUIESCED — session drained and its quiesce_check reports no queued
        or in-flight work.  Its upstream feeders are ordinary sources in
        this same conjunction, so pending upstream data keeps the loop
        alive."""
        for src in self.graph.sources:
            if src.finished:
                continue
            check = getattr(src, "quiesce_check", None)
            # order matters (TOCTOU): confirm no queued/in-flight work FIRST
            # — once both are zero no new insert can start (feeding more work
            # requires a live upstream source, which fails this conjunction
            # on its own) — and only then require the session drained.  The
            # reverse order could observe an empty session, lose the race to
            # a completing invocation, and terminate with its row undrained.
            if check is not None and check() and not src.session.has_pending:
                continue
            return False
        return True

    # -- distributed tick protocol ------------------------------------------
    def _step_dist(self, plane) -> Tuple[bool, bool, bool]:
        """One coordinated commit tick across the process cluster.

        Replaces the reference's timely progress protocol at commit
        boundaries (workers agree a timestamp is closed before results flow
        downstream — docs/.../10.worker-architecture.md:46-49): every rank
        polls its own sources, the ranks exchange (proposed_ts, moved,
        finished) in one small all-to-all, and everyone deterministically
        adopts ``max(proposals)`` as the tick timestamp, so commit
        timestamps AGREE across replicas without a distinguished
        coordinator round-trip.  Source rows are then placed by ownership
        (filter / all-to-all / broadcast, per source mode) and propagation
        runs the BSP exchange sweep."""
        from ..internals.keys import shard_of, shards_of
        from .delta import empty_delta

        rnd = self._ctrl_seq
        self._ctrl_seq += 1
        polled = []
        local_moved = False
        for src in self.graph.sources:
            mode = getattr(src, "dist_mode", "replicated")
            if mode == "partitioned":
                # defer event->delta resolution until after the exchange:
                # upsert/delete-by-key events must resolve against the KEY
                # OWNER's store, and this rank may have read another owner's
                # rows (disjoint file splits)
                events = src.session.drain()
                if events:
                    local_moved = True
                polled.append(events)
            else:
                delta = src.poll(0)
                if delta is not None and delta.n:
                    local_moved = True
                polled.append(delta)
        finished_local = self._sources_finished()
        proposal = (
            next_timestamp(),
            local_moved,
            finished_local,
            self._terminate.is_set(),
        )
        status = plane.all_to_all("tick", rnd, [proposal] * plane.nproc)
        ts = max(s[0] for s in status)
        ts = max(ts, self.current_ts + 2)
        ts += ts % 2
        bump_timestamp(ts)
        self.current_ts = ts
        moved_any = any(s[1] for s in status)
        finished_all = all(s[2] for s in status)
        stop_any = any(s[3] for s in status)

        initial: List[Tuple[EngineOperator, int, Delta]] = []
        for src, polled_item in zip(self.graph.sources, polled):
            mode = getattr(src, "dist_mode", "replicated")
            names = src.output.column_names
            if mode == "partitioned":
                # each rank read a disjoint split (fs parallel readers,
                # reference parallel_readers dataflow.rs:3317): route RAW
                # events to their key owner, then resolve upsert/delete
                # chains there with the owner's store in view
                events = polled_item or []
                parts: List[list] = [[] for _ in range(plane.nproc)]
                for ev in events:
                    if ev[0] == _COLUMNAR:
                        # split one columnar batch into per-owner columnar
                        # sub-batches (vectorized; stays tuple-free)
                        keys, cols = ev[2]
                        owners = shards_of(keys, plane.nproc)
                        for peer in range(plane.nproc):
                            mask = owners == peer
                            m = int(mask.sum())
                            if m:
                                parts[peer].append(
                                    (
                                        _COLUMNAR,
                                        m,
                                        (
                                            keys[mask],
                                            {c: v[mask] for c, v in cols.items()},
                                        ),
                                    )
                                )
                        continue
                    parts[shard_of(ev[1], plane.nproc)].append(ev)
                got = plane.all_to_all(f"src{src.id}", rnd, parts)
                merged = [ev for part in got for ev in part]
                d = src.events_to_delta(merged) or empty_delta(names)
            elif mode == "replicated":
                # every rank polls the identical event stream (script-local /
                # static sources): keep the owned-key slice, drop the rest
                d = polled_item if polled_item is not None else empty_delta(names)
                if d.n:
                    d = d.select_rows(shards_of(d.keys, plane.nproc) == plane.rank)
            elif mode == "broadcast":
                # one rank reads (e.g. a REST frontend); every rank gets the
                # full stream (feeds replicated/SPMD pipelines)
                d = polled_item if polled_item is not None else empty_delta(names)
                got = plane.all_to_all(f"src{src.id}", rnd, [d] * plane.nproc)
                d = Delta.concat([x for x in got if x.n], names)
            else:  # pragma: no cover - unknown mode
                raise ValueError(f"unknown source dist_mode {mode!r}")
            if d.n:
                d = d.consolidated()
                src.output.store.apply(d)
                for consumer, port in src.output.consumers:
                    initial.append((consumer, port, d))
        self.graph.propagate(initial, ts)  # always: BSP exchange alignment
        self.graph.tick_end(ts)
        if self.on_tick is not None:
            self.on_tick(ts)
        return moved_any, finished_all, stop_any
