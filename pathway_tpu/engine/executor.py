"""The engine run loop.

The reference's per-worker hot loop is ``probers → flushers → pollers →
worker.step_or_park`` (src/engine/dataflow.rs:5596-5650).  Here one host
drives the whole graph: each iteration polls every source session, stamps a
new commit tick (even unix-ms, matching the reference's alt-neu even-time
convention, src/engine/time.rs:22-28), propagates the resulting deltas in
topological order, and fires tick-end hooks.  In batch mode (all sources
static/finished) the loop drains and returns; in streaming mode it parks for
``commit_duration`` between ticks until terminated.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, List, Optional, Tuple

from .delta import Delta
from .graph import EngineGraph, EngineOperator
from .operators.io import SourceOperator

__all__ = ["Executor", "Timestamp", "next_timestamp"]

Timestamp = int

_last_ts_lock = threading.Lock()
_last_ts = 0


def next_timestamp() -> Timestamp:
    """Monotone even-millisecond timestamps (reference Timestamp::new_from_current_time,
    src/engine/time.rs:20-28)."""
    global _last_ts
    with _last_ts_lock:
        ts = int(_time.time() * 1000)
        ts += ts % 2  # round up to even
        if ts <= _last_ts:
            ts = _last_ts + 2
        _last_ts = ts
        return ts


class Executor:
    def __init__(
        self,
        graph: EngineGraph,
        commit_duration_ms: int = 100,
        on_tick: Optional[Callable[[Timestamp], None]] = None,
    ):
        self.graph = graph
        self.commit_duration_ms = commit_duration_ms
        self.on_tick = on_tick
        self._terminate = threading.Event()
        self.current_ts: Timestamp = 0

    def terminate(self) -> None:
        self._terminate.set()

    def step(self, ts: Optional[Timestamp] = None) -> bool:
        """Poll all sources once and propagate; returns True if any data moved."""
        ts = ts if ts is not None else next_timestamp()
        self.current_ts = ts
        initial: List[Tuple[EngineOperator, int, Delta]] = []
        for src in self.graph.sources:
            delta = src.poll(ts)
            if delta is not None and delta.n > 0:
                delta = delta.consolidated()
                src.output.store.apply(delta)
                for consumer, port in src.output.consumers:
                    initial.append((consumer, port, delta))
        moved = bool(initial)
        if initial:
            self.graph.propagate(initial, ts)
        self.graph.tick_end(ts)
        if self.on_tick is not None:
            self.on_tick(ts)
        return moved

    def run(self, bootstrap=None) -> None:
        """Run until all sources are finished (and drained) or terminated.

        ``bootstrap``: (operator, port, delta) triples to inject at the first
        tick (used by incremental re-runs for operators added after a
        previous run)."""
        self.graph.finalize()
        if bootstrap:
            ts = next_timestamp()
            self.current_ts = ts
            self.graph.propagate(list(bootstrap), ts)
        while not self._terminate.is_set():
            moved = self.step()
            finished = all(src.finished for src in self.graph.sources)
            if finished and not moved:
                # final flush for buffered/time-based operators
                ts = next_timestamp()
                self.current_ts = ts
                self.graph.flush_end(ts)
                break
            if not moved:
                self._terminate.wait(self.commit_duration_ms / 1000.0)
