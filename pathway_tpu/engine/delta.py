"""Timestamped micro-batch deltas — the unit of incremental computation.

The reference engine propagates per-record ``(data, time, diff)`` updates
through differential-dataflow collections (external/differential-dataflow/,
src/engine/dataflow.rs:757).  The TPU-native redesign batches updates: a
``Delta`` is a *columnar* batch of keyed upserts/retractions produced at one
commit tick.  Columnar batches are what vectorised host evaluation and XLA
dispatch want — one device call per operator per tick, not per record.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..internals import dtype as dt
from ..internals.keys import KEY_DTYPE

__all__ = ["Delta", "RowStore", "empty_delta", "rows_equal", "values_equal"]


def values_equal(a: Any, b: Any) -> bool:
    """Value equality that is safe for np.ndarray cells.  NaN counts as
    equal to NaN (value-identity semantics): a retraction rebuilt with the
    same NaN cell must match the stored row, or the retraction would be
    silently skipped and the row leak (RowStore.apply)."""
    if a is b:
        return True
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        if not isinstance(a, np.ndarray) or not isinstance(b, np.ndarray):
            return False
        if a.shape != b.shape:
            return False
        try:
            return bool(np.array_equal(a, b, equal_nan=True))
        except TypeError:  # non-numeric dtypes reject equal_nan
            return bool(np.array_equal(a, b))
    if isinstance(a, tuple) and isinstance(b, tuple):
        return rows_equal(a, b)
    if isinstance(a, float) and isinstance(b, float):
        return a == b or (a != a and b != b)
    try:
        return bool(a == b)
    except (ValueError, TypeError):
        return False


def rows_equal(a: Optional[Tuple[Any, ...]], b: Optional[Tuple[Any, ...]]) -> bool:
    if a is None or b is None:
        return a is b
    try:
        # C-level tuple compare: the common all-scalar case never reaches the
        # per-value Python loop.  A True is always trustworthy; a False is
        # trustworthy unless a NaN cell (x != x) compared false to itself.
        if a == b:
            return True
        if all(x == x for x in a):
            return False
    except (ValueError, TypeError):
        pass  # ndarray cells: ambiguous truth value — take the careful path
    if len(a) != len(b):
        return False
    return all(values_equal(x, y) for x, y in zip(a, b))


def _object_array(values: Sequence[Any]) -> np.ndarray:
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def as_column(values: Sequence[Any], dtype: Optional[dt.DType] = None) -> np.ndarray:
    """Build a column array; dense numpy when the dtype allows it."""
    if isinstance(values, np.ndarray) and values.dtype != object:
        return values
    npdt = dt.numpy_dtype_for(dtype) if dtype is not None else None
    if npdt is not None:
        try:
            return np.asarray(values, dtype=npdt)
        except (TypeError, ValueError, OverflowError):
            # OverflowError: out-of-int64 values stay python big ints in an
            # object column (the row-path behavior)
            pass
    return _object_array(list(values))


@dataclass
class Delta:
    """A batch of changes: row i means (keys[i], diff[i], {col: columns[col][i]}).

    diffs are +1 (insert) / -1 (retract).  Within one Delta a key may appear
    twice (retract old row + insert new row) — retractions sort first."""

    keys: np.ndarray  # uint64[n]
    diffs: np.ndarray  # int64[n]
    columns: Dict[str, np.ndarray]  # each len n

    def __post_init__(self):
        self.keys = np.asarray(self.keys, dtype=KEY_DTYPE)
        self.diffs = np.asarray(self.diffs, dtype=np.int64)

    @property
    def n(self) -> int:
        return len(self.keys)

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def column_names(self) -> List[str]:
        return list(self.columns.keys())

    def select_rows(self, mask_or_index: np.ndarray) -> "Delta":
        return Delta(
            keys=self.keys[mask_or_index],
            diffs=self.diffs[mask_or_index],
            columns={k: v[mask_or_index] for k, v in self.columns.items()},
        )

    def retractions(self) -> "Delta":
        return self.select_rows(self.diffs < 0)

    def insertions(self) -> "Delta":
        return self.select_rows(self.diffs > 0)

    def with_columns(self, columns: Dict[str, np.ndarray]) -> "Delta":
        return Delta(keys=self.keys, diffs=self.diffs, columns=columns)

    def with_keys(self, keys: np.ndarray) -> "Delta":
        return Delta(keys=keys, diffs=self.diffs, columns=self.columns)

    def rows(self) -> Iterable[Tuple[int, int, Tuple[Any, ...]]]:
        names = self.column_names
        for i in range(self.n):
            yield (
                int(self.keys[i]),
                int(self.diffs[i]),
                tuple(self.columns[c][i] for c in names),
            )

    @staticmethod
    def from_rows(
        column_names: Sequence[str],
        rows: Sequence[Tuple[int, int, Tuple[Any, ...]]],
        dtypes: Optional[Mapping[str, dt.DType]] = None,
    ) -> "Delta":
        keys = np.array([r[0] for r in rows], dtype=KEY_DTYPE)
        diffs = np.array([r[1] for r in rows], dtype=np.int64)
        columns = {}
        for ci, name in enumerate(column_names):
            vals = [r[2][ci] for r in rows]
            columns[name] = as_column(vals, dtypes.get(name) if dtypes else None)
        return Delta(keys=keys, diffs=diffs, columns=columns)

    @staticmethod
    def concat(deltas: Sequence["Delta"], column_names: Sequence[str]) -> "Delta":
        deltas = [d for d in deltas if d.n > 0]
        if not deltas:
            return empty_delta(column_names)
        if len(deltas) == 1:
            return deltas[0]
        keys = np.concatenate([d.keys for d in deltas])
        diffs = np.concatenate([d.diffs for d in deltas])
        columns = {}
        for name in column_names:
            cols = [d.columns[name] for d in deltas]
            if any(c.dtype == object for c in cols):
                cols = [c.astype(object) for c in cols]
            columns[name] = np.concatenate(cols)
        return Delta(keys=keys, diffs=diffs, columns=columns)

    def consolidated(self) -> "Delta":
        """Cancel exact insert/retract pairs per key, then order retractions
        before insertions (stable).

        Cancellation makes the ordering safe: ``RowStore.apply`` replays a
        delta positionally, so an uncancelled (−new, +new) pair from a
        delete-after-update transient, re-sorted retractions-first, would
        resurrect the deleted row.  Removing equal-and-opposite pairs
        preserves the multiset sum (aggregates unaffected) and leaves at most
        one retraction + one insertion per key in well-formed streams."""
        if self.n <= 1:
            return self
        keys = self.keys
        diffs = self.diffs
        keep = np.ones(self.n, dtype=bool)
        # cancellation needed only for keys carrying both polarities —
        # find those rows vectorised so the common single-upsert-in-a-bulk
        # delta never enters a python loop
        uniq, inv = np.unique(keys, return_inverse=True)
        if len(uniq) < self.n:
            has_pos = np.bincount(inv, weights=(diffs > 0)) > 0
            has_neg = np.bincount(inv, weights=(diffs < 0)) > 0
            mixed_rows = np.flatnonzero(has_pos[inv] & has_neg[inv])
            names = self.column_names
            cols = [self.columns[c] for c in names]
            groups: Dict[int, List[int]] = {}
            for i in mixed_rows:
                groups.setdefault(int(inv[i]), []).append(int(i))
            for idxs in groups.values():
                pos = [i for i in idxs if diffs[i] > 0]
                neg = [i for i in idxs if diffs[i] < 0]
                if len(pos) > 4 and len(neg) > 4:
                    # large group: match exact insert/retract pairs by
                    # serialized bytes first (linear), leaving only unmatched
                    # leftovers for the quadratic rows_equal scan — pickle
                    # equality implies value equality, but not vice versa
                    # (int vs np.int64), so leftovers still need the scan
                    import pickle

                    buckets: Dict[bytes, List[int]] = {}
                    unbucketed_pos: List[int] = []
                    for pi in pos:
                        try:
                            b = pickle.dumps(tuple(c[pi] for c in cols), 4)
                        except Exception:
                            unbucketed_pos.append(pi)
                            continue
                        buckets.setdefault(b, []).append(pi)
                    leftover_neg: List[int] = []
                    for ni in neg:
                        try:
                            b = pickle.dumps(tuple(c[ni] for c in cols), 4)
                        except Exception:
                            leftover_neg.append(ni)
                            continue
                        lst = buckets.get(b)
                        if lst:
                            pi = lst.pop()
                            keep[ni] = False
                            keep[pi] = False
                        else:
                            leftover_neg.append(ni)
                    pos = unbucketed_pos + [
                        pi for lst in buckets.values() for pi in lst
                    ]
                    neg = leftover_neg
                for ni in neg:
                    nrow = tuple(c[ni] for c in cols)
                    for pj, pi in enumerate(pos):
                        if pi is None:
                            continue
                        if rows_equal(tuple(c[pi] for c in cols), nrow):
                            keep[ni] = False
                            keep[pi] = False
                            pos[pj] = None
                            break
            if not keep.all():
                sub = self.select_rows(keep)
                if sub.n <= 1:
                    return sub
                order = np.argsort(sub.diffs, kind="stable")
                return sub.select_rows(order)
        order = np.argsort(diffs, kind="stable")
        if np.all(order == np.arange(self.n)):
            return self
        return self.select_rows(order)


def empty_delta(column_names: Sequence[str]) -> Delta:
    return Delta(
        keys=np.empty(0, dtype=KEY_DTYPE),
        diffs=np.empty(0, dtype=np.int64),
        columns={c: np.empty(0, dtype=object) for c in column_names},
    )


class RowStore:
    """Materialised current state of a table: key → row tuple.

    The engine keeps one RowStore per engine table so any operator can
    retract previously-emitted rows and stateful operators can look rows up
    (the analog of differential arrangements,
    external/differential-dataflow/ — but as plain indexed state since each
    delta application is a host-side batch)."""

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)
        self._rows: Dict[int, Tuple[Any, ...]] = {}

    def __len__(self) -> int:
        return len(self._rows)

    def __contains__(self, key: int) -> bool:
        return int(key) in self._rows

    def get(self, key: int) -> Optional[Tuple[Any, ...]]:
        return self._rows.get(int(key))

    def items(self):
        return self._rows.items()

    def keys_array(self) -> np.ndarray:
        return np.fromiter(self._rows.keys(), dtype=KEY_DTYPE, count=len(self._rows))

    def apply(self, delta: Delta) -> None:
        """Replay a delta into the state — columnar: the common shapes
        (all-insert, or retractions-then-insertions as ``consolidated()``
        emits) run as C-level zip/update/pop bulk ops, never a per-row
        Python tuple build.  ``list(col)`` (not ``col.tolist()``) keeps
        np scalar types intact — np.uint64 cells are pointers
        (internals/keys.py:53) and must not decay to plain ints."""
        n = delta.n
        if n == 0:
            return
        names = self.column_names
        cols = [delta.columns[c] for c in names]
        diffs = delta.diffs
        rows = self._rows
        neg = int(np.searchsorted(diffs, 0))  # first non-negative diff
        if neg == 0 or not (diffs[:neg] < 0).all() or not (diffs[neg:] > 0).all():
            if (diffs > 0).all():
                neg = 0
            else:
                # unsorted mixed delta: positional replay (rare — only
                # un-consolidated callers)
                for i in range(n):
                    key = int(delta.keys[i])
                    if diffs[i] > 0:
                        rows[key] = tuple(c[i] for c in cols)
                    else:
                        cur = rows.get(key)
                        if cur is None or rows_equal(
                            cur, tuple(c[i] for c in cols)
                        ):
                            rows.pop(key, None)
                return
        keys = delta.keys.tolist()
        if neg:
            # value-aware retraction: deltas from different upstream ports
            # arrive in arbitrary order within a tick, so a stale retraction
            # (old row) may land AFTER the key's new row was stored — only
            # pop when the stored row is the one being retracted
            if cols:
                ret_rows = zip(*(list(c[:neg]) for c in cols))
            else:
                ret_rows = iter([()] * neg)
            for key, row in zip(keys[:neg], ret_rows):
                cur = rows.get(key)
                if cur is None or rows_equal(cur, row):
                    rows.pop(key, None)
        if neg < n:
            ins_keys = keys[neg:]
            if cols:
                ins_rows = zip(*(list(c[neg:]) for c in cols))
            else:
                ins_rows = iter([()] * len(ins_keys))
            rows.update(zip(ins_keys, ins_rows))

    def _columns_of(self, rows: List[Tuple[Any, ...]]) -> Dict[str, np.ndarray]:
        """Transpose row tuples into object columns (C-level zip)."""
        if rows:
            transposed = list(zip(*rows))
        else:
            transposed = [()] * len(self.column_names)
        return {
            name: _object_array(transposed[ci])
            for ci, name in enumerate(self.column_names)
        }

    def lookup_delta(self, keys: np.ndarray, diff: int = -1) -> Delta:
        """Build a delta of current rows for the given keys (used to retract)."""
        get = self._rows.get
        pairs = [
            (key, row)
            for key in np.asarray(keys, dtype=KEY_DTYPE).tolist()
            if (row := get(key)) is not None
        ]
        found_keys = [p[0] for p in pairs]
        return Delta(
            keys=np.array(found_keys, dtype=KEY_DTYPE),
            diffs=np.full(len(found_keys), diff, dtype=np.int64),
            columns=self._columns_of([p[1] for p in pairs]),
        )

    def to_delta(self, diff: int = 1) -> Delta:
        """Snapshot the entire state as one insertion delta."""
        return Delta(
            keys=self.keys_array(),
            diffs=np.full(len(self._rows), diff, dtype=np.int64),
            columns=self._columns_of(list(self._rows.values())),
        )

    def to_columns(self) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        return self.keys_array(), self._columns_of(list(self._rows.values()))
