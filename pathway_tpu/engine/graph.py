"""Engine dataflow graph: tables, operators, scheduler.

The reference's engine is a ~60-method ``Graph`` trait implemented over
timely/differential scopes with one graph instance per worker thread
(src/engine/graph.rs:664, src/engine/dataflow.rs:757).  The TPU-native
engine is a host-side operator DAG driven in topological order once per
commit tick; each operator transforms columnar ``Delta`` batches, and
device-heavy operators (batched ML UDFs, the KNN index) dispatch jitted XLA
computations inside their ``process``.

Distribution is two-plane: device state shards over the jax mesh *inside*
the ops (XLA collectives over ICI/DCN — SURVEY.md §5.8), while the host
relational plane shards BY ROW KEY across cluster processes — every rank
runs this same DAG on its key slice, and exchange edges (``dist_routing``)
move rows between ranks over ``parallel/exchange.py`` exactly where the
reference reshards timely collections (src/engine/dataflow.rs:3314).
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..internals.keys import KEY_DTYPE
from ..internals.error_log import set_current_operator
from ..internals.trace import reraise_with_trace
from .delta import Delta, RowStore, empty_delta

__all__ = ["EngineTable", "EngineOperator", "EngineGraph", "OutputCallbacks"]


class EngineTable:
    """A node carrying rows: column names + materialised RowStore."""

    _ids = itertools.count()

    def __init__(self, column_names: Sequence[str], name: str = ""):
        self.id = next(EngineTable._ids)
        self.name = name or f"t{self.id}"
        self.column_names = list(column_names)
        self.store = RowStore(self.column_names)
        self.consumers: List[Tuple["EngineOperator", int]] = []
        self.producer: Optional["EngineOperator"] = None

    def empty_delta(self) -> Delta:
        return empty_delta(self.column_names)

    def __repr__(self):  # pragma: no cover
        return f"<EngineTable {self.name}({', '.join(self.column_names)})>"


class EngineOperator:
    """Base operator: consumes deltas on input ports, emits one output delta.

    Contract (incremental correctness): ``process`` is called sequentially in
    topological order within a tick; input table stores are already updated
    with the incoming delta, the operator's own output store is updated by
    the scheduler *after* ``process`` returns (so retraction lookups against
    ``self.output.store`` see the pre-update state).  Stateful operators keep
    their *own* per-port state and update it inside ``process`` (the
    bilinear-rule discipline: port-0 deltas join pre-update port-1 own state,
    and vice versa)."""

    _ids = itertools.count()

    def __init__(
        self,
        inputs: Sequence[EngineTable],
        output: Optional[EngineTable],
        name: str = "",
    ):
        self.id = next(EngineOperator._ids)
        self.name = name or type(self).__name__
        self.inputs = list(inputs)
        self.output = output
        self.topo_index: int = -1
        self.trace: Any = None  # user stack frame (internals/trace.py)
        # scrape-time observability (internals/metrics.py /metrics endpoint)
        self.rows_in: int = 0
        self.rows_out: int = 0
        self.process_ns: int = 0
        # per-tick latency probe (reference Prober/ProberStats,
        # src/engine/progress_reporter.rs): time spent in this operator
        # during the last completed tick
        self.last_tick_ns: int = 0
        self._tick_acc_ns: int = 0
        for port, table in enumerate(self.inputs):
            table.consumers.append((self, port))
        if output is not None:
            output.producer = self

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        raise NotImplementedError

    def dist_routing(self, port: int):
        """How this input port's rows are placed across cluster processes in
        distributed runs (reference: per-operator exchange pacts on timely
        collections — reshard by key shard, src/engine/dataflow/shard.rs:6).

        Returns one of:
          None        — row-local: any placement works (pure rowwise ops);
          "key"       — co-locate by the delta's row key (owner = key shard);
          callable    — computed routing keys: fn(delta) -> uint64[n]
                        (groupby routes by group key, join by join key);
          "gather"    — all rows to rank 0 (global operators: sort, sinks);
          "replicate" — every rank sees every row (device-mesh operators
                        whose jit calls must stay SPMD across processes).
        The safe default for stateful operators is "gather"."""
        return "gather"

    def on_tick_end(self, ts: int) -> Optional[Delta]:
        """Called once per tick after all deltas settle (for time-based ops
        like buffers / forget)."""
        return None

    def on_end(self) -> Optional[Delta]:
        """Called when all sources are exhausted (flush buffers)."""
        return None

    def snapshot_state(self):
        """Serializable operator state for OPERATOR_PERSISTING checkpoints
        (reference operator_snapshot.rs); stateless operators raise."""
        raise NotImplementedError

    def restore_state(self, state) -> None:
        raise NotImplementedError

    def __repr__(self):  # pragma: no cover
        return f"<{self.name}#{self.id}>"


class OutputCallbacks:
    """Subscriber callbacks (reference SubscribeCallbacks, graph.rs:581)."""

    def __init__(
        self,
        on_change: Optional[Callable[[int, Tuple[Any, ...], int, int], None]] = None,
        on_time_end: Optional[Callable[[int], None]] = None,
        on_end: Optional[Callable[[], None]] = None,
    ):
        self.on_change = on_change
        self.on_time_end = on_time_end
        self.on_end = on_end


class EngineGraph:
    """Container for the lowered dataflow; assigns topological order."""

    def __init__(self):
        self.tables: List[EngineTable] = []
        self.operators: List[EngineOperator] = []
        self.sources: List["SourceOperator"] = []
        self.sinks: List[EngineOperator] = []
        # distributed run state (set by the Executor when PATHWAY_PROCESSES>1):
        # the host exchange plane, a BSP round counter, and per-edge routing
        self.plane = None
        self._round = 0
        self._topo_ops: List[EngineOperator] = []
        self._edge_layout: Dict[Tuple[int, int], str] = {}

    def add_table(self, column_names: Sequence[str], name: str = "") -> EngineTable:
        t = EngineTable(column_names, name)
        self.tables.append(t)
        return t

    def add_operator(self, op: EngineOperator) -> EngineOperator:
        self.operators.append(op)
        if op.trace is None:
            from ..internals.trace import trace_user_frame

            op.trace = trace_user_frame()
        from .operators.io import SourceOperator  # local import to avoid cycle

        if isinstance(op, SourceOperator):
            self.sources.append(op)
        return op

    def finalize(self) -> None:
        """Topologically order operators (graph is a DAG by construction)."""
        indegree: Dict[int, int] = {}
        ops_by_id = {op.id: op for op in self.operators}
        dependents: Dict[int, List[int]] = {op.id: [] for op in self.operators}
        for op in self.operators:
            deg = 0
            for t in op.inputs:
                if t.producer is not None:
                    deg += 1
                    dependents[t.producer.id].append(op.id)
            indegree[op.id] = deg
        ready = [op.id for op in self.operators if indegree[op.id] == 0]
        heapq.heapify(ready)
        order = 0
        while ready:
            oid = heapq.heappop(ready)
            ops_by_id[oid].topo_index = order
            order += 1
            for dep in dependents[oid]:
                indegree[dep] -= 1
                if indegree[dep] == 0:
                    heapq.heappush(ready, dep)
        if order != len(self.operators):
            raise RuntimeError("cycle detected in dataflow graph")
        self._topo_ops = sorted(self.operators, key=lambda o: o.topo_index)
        if self.plane is not None:
            self._infer_layouts()

    def _infer_layouts(self) -> None:
        """Static placement analysis for distributed runs.  Each table is
        either "sharded" (the global stream is the disjoint union of the
        ranks' local streams) or "replicated" (every rank holds the full
        stream).  The distinction decides what an exchange edge does at
        runtime: routing a REPLICATED input by key must *filter* locally
        (every rank already has every row — a network exchange would
        duplicate rows N times), while routing a SHARDED input is a real
        all-to-all.  Mirrors the reference's static exchange placement on
        timely collections (src/engine/dataflow.rs:3314 reshard)."""
        layout: Dict[int, str] = {}
        for op in self._topo_ops:
            from .operators.io import SourceOperator

            if isinstance(op, SourceOperator):
                mode = getattr(op, "dist_mode", "replicated")
                layout[op.output.id] = (
                    "replicated" if mode == "broadcast" else "sharded"
                )
                continue
            effective = []
            for port, table in enumerate(op.inputs):
                routing = op.dist_routing(port)
                in_layout = layout.get(table.id, "sharded")
                if routing == "replicate":
                    eff = "replicated"
                elif routing is None:
                    eff = in_layout
                else:  # "key" / callable / "gather" all yield sharded placements
                    eff = "sharded"
                self._edge_layout[(op.id, port)] = in_layout
                effective.append(eff)
            if op.output is not None:
                layout[op.output.id] = (
                    "replicated"
                    if effective and all(e == "replicated" for e in effective)
                    else "sharded"
                )

    def _exchange(self, op: EngineOperator, port: int, delta: Delta, rnd: int) -> Delta:
        """Apply this edge's routing through the exchange plane (BSP: every
        rank calls this for every exchange edge every round, in the same
        order)."""
        from ..internals.keys import shards_of

        plane = self.plane
        routing = op.dist_routing(port)
        names = op.inputs[port].column_names
        in_layout = self._edge_layout.get((op.id, port), "sharded")
        edge = f"e{op.id}.{port}"
        if routing is None:
            return delta
        if routing == "replicate":
            if in_layout == "replicated":
                return delta
            parts = [delta] * plane.nproc
            got = plane.all_to_all(edge, rnd, parts)
            return Delta.concat([d for d in got if d.n], names)
        if routing == "gather":
            if in_layout == "replicated":
                return delta if plane.rank == 0 else empty_delta(names)
            got = plane.gather(edge, rnd, delta)
            if got is None:
                return empty_delta(names)
            return Delta.concat([d for d in got if d.n], names)
        # "key" or computed-key routing
        if routing == "key":
            route_keys = delta.keys
        else:
            try:
                route_keys = routing(delta) if delta.n else delta.keys
            except Exception as exc:
                reraise_with_trace(op, exc)
        owners = shards_of(np.asarray(route_keys, dtype=KEY_DTYPE), plane.nproc)
        if in_layout == "replicated":
            # every rank holds the full stream: keep the owned slice locally
            return delta.select_rows(owners == plane.rank)
        parts = [delta.select_rows(owners == p) for p in range(plane.nproc)]
        got = plane.all_to_all(edge, rnd, parts)
        return Delta.concat([d for d in got if d.n], names)

    def propagate(self, initial: List[Tuple[EngineOperator, int, Delta]], ts: int) -> None:
        """Push deltas through the graph in topological order for one tick."""
        if self.plane is not None:
            return self._propagate_dist(initial, ts)
        # priority queue keyed by (topo_index, seq) so operators fire in order
        seq = itertools.count()
        heap: List[Tuple[int, int, EngineOperator, int, Delta]] = []
        for op, port, delta in initial:
            heapq.heappush(heap, (op.topo_index, next(seq), op, port, delta))
        while heap:
            _, _, op, port, delta = heapq.heappop(heap)
            if delta.n == 0 and port >= 0:
                continue
            out = self._run_op(op, port, delta, ts)
            if out is not None and out.n > 0 and op.output is not None:
                out = out.consolidated()
                op.rows_out += out.n
                op.output.store.apply(out)
                for consumer, cport in op.output.consumers:
                    heapq.heappush(
                        heap, (consumer.topo_index, next(seq), consumer, cport, out)
                    )

    def _propagate_dist(self, initial: List[Tuple[EngineOperator, int, Delta]], ts: int) -> None:
        """Distributed tick propagation: a strict topological sweep in which
        every rank visits every exchange edge exactly once per round (BSP) —
        the deterministic global order is what makes the plane's collectives
        deadlock-free.  Exchange edges run even when the local delta is empty
        (a peer may be routing rows here); row-local edges behave exactly
        like the single-process heap path."""
        rnd = self._round
        self._round += 1
        pending: Dict[Tuple[int, int], List[Delta]] = {}
        for op, port, delta in initial:
            pending.setdefault((op.id, port), []).append(delta)
        from .operators.io import SourceOperator

        for op in self._topo_ops:
            if isinstance(op, SourceOperator):
                continue
            for port in range(len(op.inputs)):
                names = op.inputs[port].column_names
                deltas = pending.pop((op.id, port), None)
                merged = (
                    Delta.concat(deltas, names) if deltas else empty_delta(names)
                )
                if op.dist_routing(port) is not None:
                    merged = self._exchange(op, port, merged, rnd)
                if merged.n == 0:
                    continue
                merged = merged.consolidated()
                if merged.n == 0:
                    continue
                out = self._run_op(op, port, merged, ts)
                if out is not None and out.n > 0 and op.output is not None:
                    out = out.consolidated()
                    op.rows_out += out.n
                    op.output.store.apply(out)
                    for consumer, cport in op.output.consumers:
                        pending.setdefault((consumer.id, cport), []).append(out)

    def _run_op(self, op: EngineOperator, port: int, delta: Delta, ts: int):
        """Execute one operator on one delta with error attribution + the
        per-operator latency/row probes (shared by the single-process heap
        path and the distributed sweep)."""
        t0 = _time.perf_counter_ns()
        set_current_operator(op)
        try:
            out = op.process(port, delta, ts)
        except Exception as exc:
            reraise_with_trace(op, exc)
        finally:
            set_current_operator(None)
        elapsed = _time.perf_counter_ns() - t0
        op.process_ns += elapsed
        op._tick_acc_ns += elapsed
        op.rows_in += delta.n
        return out

    def _collect(self, op, out, pending) -> None:
        """Queue an operator's tick-end/flush output; ``out`` is either a
        Delta for ``op.output`` or a list of (table, delta) for multi-output
        operators (iterate)."""
        if out is None:
            return
        if isinstance(out, list):
            for table, delta in out:
                if delta is None or delta.n == 0:
                    continue
                delta = delta.consolidated()
                table.store.apply(delta)
                for consumer, cport in table.consumers:
                    pending.append((consumer, cport, delta))
            return
        if out.n > 0 and op.output is not None:
            out = out.consolidated()
            op.output.store.apply(out)
            for consumer, cport in op.output.consumers:
                pending.append((consumer, cport, out))

    def tick_end(self, ts: int) -> None:
        """Run on_tick_end hooks (time-based operators may release buffers)."""
        pending: List[Tuple[EngineOperator, int, Delta]] = []
        for op in sorted(self.operators, key=lambda o: o.topo_index):
            set_current_operator(op)
            try:
                out = op.on_tick_end(ts)
            except Exception as exc:
                reraise_with_trace(op, exc)
            finally:
                set_current_operator(None)
            self._collect(op, out, pending)
        if pending or self.plane is not None:
            # distributed: ranks must run the SAME number of propagate rounds
            # per tick (every round walks every exchange edge), so tick-end
            # propagation happens even when locally empty
            self.propagate(pending, ts)
        # roll the per-tick latency probes (progress_reporter.rs analog)
        for op in self.operators:
            op.last_tick_ns = op._tick_acc_ns
            op._tick_acc_ns = 0

    def flush_end(self, ts: int) -> None:
        pending: List[Tuple[EngineOperator, int, Delta]] = []
        for op in sorted(self.operators, key=lambda o: o.topo_index):
            set_current_operator(op)
            try:
                out = op.on_end()
            except Exception as exc:
                reraise_with_trace(op, exc)
            finally:
                set_current_operator(None)
            self._collect(op, out, pending)
        if pending or self.plane is not None:
            self.propagate(pending, ts)
