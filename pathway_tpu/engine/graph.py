"""Engine dataflow graph: tables, operators, scheduler.

The reference's engine is a ~60-method ``Graph`` trait implemented over
timely/differential scopes with one graph instance per worker thread
(src/engine/graph.rs:664, src/engine/dataflow.rs:757).  The TPU-native
engine is a single host-side operator DAG driven in topological order once
per commit tick; each operator transforms columnar ``Delta`` batches, and
device-heavy operators (batched ML UDFs, the KNN index) dispatch jitted XLA
computations inside their ``process``.  Distribution happens *inside* the
device ops via ``jax.sharding`` over the mesh — not by running N copies of
the dataflow — which is the SPMD-native analog of the reference's
worker-sharded dataflow (SURVEY.md §5.8).
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..internals.keys import KEY_DTYPE
from ..internals.trace import reraise_with_trace
from .delta import Delta, RowStore, empty_delta

__all__ = ["EngineTable", "EngineOperator", "EngineGraph", "OutputCallbacks"]


class EngineTable:
    """A node carrying rows: column names + materialised RowStore."""

    _ids = itertools.count()

    def __init__(self, column_names: Sequence[str], name: str = ""):
        self.id = next(EngineTable._ids)
        self.name = name or f"t{self.id}"
        self.column_names = list(column_names)
        self.store = RowStore(self.column_names)
        self.consumers: List[Tuple["EngineOperator", int]] = []
        self.producer: Optional["EngineOperator"] = None

    def empty_delta(self) -> Delta:
        return empty_delta(self.column_names)

    def __repr__(self):  # pragma: no cover
        return f"<EngineTable {self.name}({', '.join(self.column_names)})>"


class EngineOperator:
    """Base operator: consumes deltas on input ports, emits one output delta.

    Contract (incremental correctness): ``process`` is called sequentially in
    topological order within a tick; input table stores are already updated
    with the incoming delta, the operator's own output store is updated by
    the scheduler *after* ``process`` returns (so retraction lookups against
    ``self.output.store`` see the pre-update state).  Stateful operators keep
    their *own* per-port state and update it inside ``process`` (the
    bilinear-rule discipline: port-0 deltas join pre-update port-1 own state,
    and vice versa)."""

    _ids = itertools.count()

    def __init__(
        self,
        inputs: Sequence[EngineTable],
        output: Optional[EngineTable],
        name: str = "",
    ):
        self.id = next(EngineOperator._ids)
        self.name = name or type(self).__name__
        self.inputs = list(inputs)
        self.output = output
        self.topo_index: int = -1
        self.trace: Any = None  # user stack frame (internals/trace.py)
        # scrape-time observability (internals/metrics.py /metrics endpoint)
        self.rows_in: int = 0
        self.rows_out: int = 0
        self.process_ns: int = 0
        # per-tick latency probe (reference Prober/ProberStats,
        # src/engine/progress_reporter.rs): time spent in this operator
        # during the last completed tick
        self.last_tick_ns: int = 0
        self._tick_acc_ns: int = 0
        for port, table in enumerate(self.inputs):
            table.consumers.append((self, port))
        if output is not None:
            output.producer = self

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        raise NotImplementedError

    def on_tick_end(self, ts: int) -> Optional[Delta]:
        """Called once per tick after all deltas settle (for time-based ops
        like buffers / forget)."""
        return None

    def on_end(self) -> Optional[Delta]:
        """Called when all sources are exhausted (flush buffers)."""
        return None

    def snapshot_state(self):
        """Serializable operator state for OPERATOR_PERSISTING checkpoints
        (reference operator_snapshot.rs); stateless operators raise."""
        raise NotImplementedError

    def restore_state(self, state) -> None:
        raise NotImplementedError

    def __repr__(self):  # pragma: no cover
        return f"<{self.name}#{self.id}>"


class OutputCallbacks:
    """Subscriber callbacks (reference SubscribeCallbacks, graph.rs:581)."""

    def __init__(
        self,
        on_change: Optional[Callable[[int, Tuple[Any, ...], int, int], None]] = None,
        on_time_end: Optional[Callable[[int], None]] = None,
        on_end: Optional[Callable[[], None]] = None,
    ):
        self.on_change = on_change
        self.on_time_end = on_time_end
        self.on_end = on_end


class EngineGraph:
    """Container for the lowered dataflow; assigns topological order."""

    def __init__(self):
        self.tables: List[EngineTable] = []
        self.operators: List[EngineOperator] = []
        self.sources: List["SourceOperator"] = []
        self.sinks: List[EngineOperator] = []

    def add_table(self, column_names: Sequence[str], name: str = "") -> EngineTable:
        t = EngineTable(column_names, name)
        self.tables.append(t)
        return t

    def add_operator(self, op: EngineOperator) -> EngineOperator:
        self.operators.append(op)
        if op.trace is None:
            from ..internals.trace import trace_user_frame

            op.trace = trace_user_frame()
        from .operators.io import SourceOperator  # local import to avoid cycle

        if isinstance(op, SourceOperator):
            self.sources.append(op)
        return op

    def finalize(self) -> None:
        """Topologically order operators (graph is a DAG by construction)."""
        indegree: Dict[int, int] = {}
        ops_by_id = {op.id: op for op in self.operators}
        dependents: Dict[int, List[int]] = {op.id: [] for op in self.operators}
        for op in self.operators:
            deg = 0
            for t in op.inputs:
                if t.producer is not None:
                    deg += 1
                    dependents[t.producer.id].append(op.id)
            indegree[op.id] = deg
        ready = [op.id for op in self.operators if indegree[op.id] == 0]
        heapq.heapify(ready)
        order = 0
        while ready:
            oid = heapq.heappop(ready)
            ops_by_id[oid].topo_index = order
            order += 1
            for dep in dependents[oid]:
                indegree[dep] -= 1
                if indegree[dep] == 0:
                    heapq.heappush(ready, dep)
        if order != len(self.operators):
            raise RuntimeError("cycle detected in dataflow graph")

    def propagate(self, initial: List[Tuple[EngineOperator, int, Delta]], ts: int) -> None:
        """Push deltas through the graph in topological order for one tick."""
        # priority queue keyed by (topo_index, seq) so operators fire in order
        seq = itertools.count()
        heap: List[Tuple[int, int, EngineOperator, int, Delta]] = []
        for op, port, delta in initial:
            heapq.heappush(heap, (op.topo_index, next(seq), op, port, delta))
        while heap:
            _, _, op, port, delta = heapq.heappop(heap)
            if delta.n == 0 and port >= 0:
                continue
            t0 = _time.perf_counter_ns()
            try:
                out = op.process(port, delta, ts)
            except Exception as exc:
                reraise_with_trace(op, exc)
            elapsed = _time.perf_counter_ns() - t0
            op.process_ns += elapsed
            op._tick_acc_ns += elapsed
            op.rows_in += delta.n
            if out is not None and out.n > 0 and op.output is not None:
                out = out.consolidated()
                op.rows_out += out.n
                op.output.store.apply(out)
                for consumer, cport in op.output.consumers:
                    heapq.heappush(
                        heap, (consumer.topo_index, next(seq), consumer, cport, out)
                    )

    def _collect(self, op, out, pending) -> None:
        """Queue an operator's tick-end/flush output; ``out`` is either a
        Delta for ``op.output`` or a list of (table, delta) for multi-output
        operators (iterate)."""
        if out is None:
            return
        if isinstance(out, list):
            for table, delta in out:
                if delta is None or delta.n == 0:
                    continue
                delta = delta.consolidated()
                table.store.apply(delta)
                for consumer, cport in table.consumers:
                    pending.append((consumer, cport, delta))
            return
        if out.n > 0 and op.output is not None:
            out = out.consolidated()
            op.output.store.apply(out)
            for consumer, cport in op.output.consumers:
                pending.append((consumer, cport, out))

    def tick_end(self, ts: int) -> None:
        """Run on_tick_end hooks (time-based operators may release buffers)."""
        pending: List[Tuple[EngineOperator, int, Delta]] = []
        for op in sorted(self.operators, key=lambda o: o.topo_index):
            try:
                out = op.on_tick_end(ts)
            except Exception as exc:
                reraise_with_trace(op, exc)
            self._collect(op, out, pending)
        if pending:
            self.propagate(pending, ts)
        # roll the per-tick latency probes (progress_reporter.rs analog)
        for op in self.operators:
            op.last_tick_ns = op._tick_acc_ns
            op._tick_acc_ns = 0

    def flush_end(self, ts: int) -> None:
        pending: List[Tuple[EngineOperator, int, Delta]] = []
        for op in sorted(self.operators, key=lambda o: o.topo_index):
            try:
                out = op.on_end()
            except Exception as exc:
                reraise_with_trace(op, exc)
            self._collect(op, out, pending)
        if pending:
            self.propagate(pending, ts)
