"""Group-by reducers.

Mirrors the reference reducer set (src/engine/reduce.rs:22-594): semigroup
reducers (count / int & float / ndarray sums) update state in O(1) under
insertion *and* retraction; order-sensitive reducers (min/max/argmin/argmax,
unique, tuples) keep a per-group multiset so retractions are exact.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "Reducer",
    "CountReducer",
    "SumReducer",
    "NdarraySumReducer",
    "MinReducer",
    "MaxReducer",
    "ArgMinReducer",
    "ArgMaxReducer",
    "UniqueReducer",
    "AnyReducer",
    "SortedTupleReducer",
    "TupleReducer",
    "AvgReducer",
    "EarliestReducer",
    "LatestReducer",
    "StatefulReducer",
]


def _hashable(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return ("__ndarray__", v.dtype.str, v.shape, v.tobytes())
    if isinstance(v, (list, dict)):
        import json

        return ("__json__", json.dumps(v, sort_keys=True, default=str))
    return v


class Reducer:
    """Interface: state = update(state, value, diff, key, ts); result(state).

    Additive reducers (count/sum/avg) additionally implement the vectorised
    pair ``batch_contribs``/``merge_contrib``: a whole delta collapses to one
    per-group contribution array (np.bincount over the group inverse index),
    and only *touched groups* are visited in Python — the groupby hot path
    (engine/operators/groupby.py) uses this to stay columnar per tick, the
    micro-batch analog of the reference's count-free semigroup reducers
    (src/engine/reduce.rs:40-101)."""

    name = "reducer"
    n_args = 1

    def init_state(self) -> Any:
        raise NotImplementedError

    def update(self, state: Any, value: Any, diff: int, key: int, ts: int) -> Any:
        raise NotImplementedError

    def result(self, state: Any) -> Any:
        raise NotImplementedError

    def batch_contribs(
        self,
        args: List[np.ndarray],
        diffs: np.ndarray,
        inv: np.ndarray,
        n_groups: int,
    ) -> Any:
        """Per-group aggregated contribution for one delta (group j's value
        at index j), or None when this reducer/dtype cannot vectorise —
        order-sensitive reducers return None and take the per-row path."""
        return None

    def merge_contrib(self, state: Any, contrib: Any) -> Any:
        raise NotImplementedError


class CountReducer(Reducer):
    name = "count"
    n_args = 0

    def init_state(self):
        return 0

    def update(self, state, value, diff, key, ts):
        return state + diff

    def result(self, state):
        return state

    def batch_contribs(self, args, diffs, inv, n_groups):
        return np.bincount(inv, weights=diffs, minlength=n_groups).astype(
            np.int64
        )

    def merge_contrib(self, state, contrib):
        return state + int(contrib)


class SumReducer(Reducer):
    name = "sum"

    def init_state(self):
        return None

    def update(self, state, value, diff, key, ts):
        contrib = value * diff
        return contrib if state is None else state + contrib

    def result(self, state):
        return state

    def batch_contribs(self, args, diffs, inv, n_groups):
        v = args[0]
        if not isinstance(v, np.ndarray) or v.ndim != 1 or v.dtype == object:
            return None
        if v.dtype != np.uint64 and np.issubdtype(v.dtype, np.integer):
            acc = np.zeros(n_groups, dtype=np.int64)
            # add.at (not bincount) keeps int64 arithmetic exact
            np.add.at(acc, inv, v.astype(np.int64) * diffs)
            return acc
        if np.issubdtype(v.dtype, np.floating):
            return np.bincount(inv, weights=v * diffs, minlength=n_groups)
        return None

    def merge_contrib(self, state, contrib):
        return contrib if state is None else state + contrib


class NdarraySumReducer(Reducer):
    name = "ndarray_sum"

    def init_state(self):
        return None

    def update(self, state, value, diff, key, ts):
        contrib = np.asarray(value) * diff
        return contrib if state is None else state + contrib

    def result(self, state):
        return state


class _MultisetReducer(Reducer):
    """Base: state is {hashable(value): [count, value]}."""

    def init_state(self):
        return {}

    def update(self, state, value, diff, key, ts):
        h = _hashable(value)
        entry = state.get(h)
        if entry is None:
            entry = [0, value]
            state[h] = entry
        entry[0] += diff
        # == 0, not <= 0: within one consolidated batch a retraction may be
        # processed before its matching insertion; negative counts must
        # persist so the insertion can cancel them
        if entry[0] == 0:
            del state[h]
        return state


class MinReducer(_MultisetReducer):
    name = "min"

    def result(self, state):
        return min((e[1] for e in state.values()), default=None)


class MaxReducer(_MultisetReducer):
    name = "max"

    def result(self, state):
        return max((e[1] for e in state.values()), default=None)


class _PairMultisetReducer(Reducer):
    """Multiset of (value, payload) pairs (for argmin/argmax)."""

    def init_state(self):
        return {}

    def update(self, state, value, diff, key, ts):
        # value is a tuple (order_value, payload)
        h = _hashable(value)
        entry = state.get(h)
        if entry is None:
            entry = [0, value]
            state[h] = entry
        entry[0] += diff
        # == 0, not <= 0: within one consolidated batch a retraction may be
        # processed before its matching insertion; negative counts must
        # persist so the insertion can cancel them
        if entry[0] == 0:
            del state[h]
        return state


class ArgMinReducer(_PairMultisetReducer):
    name = "argmin"
    n_args = 2

    def result(self, state):
        if not state:
            return None
        best = min(state.values(), key=lambda e: (e[1][0], e[1][1]))
        return best[1][1]


class ArgMaxReducer(_PairMultisetReducer):
    name = "argmax"
    n_args = 2

    def result(self, state):
        if not state:
            return None
        # ties broken by smallest payload repr (deterministic across runs)
        best = max(state.values(), key=lambda e: (e[1][0], [-ord(c) for c in repr(e[1][1])]))
        return best[1][1]


class UniqueReducer(_MultisetReducer):
    name = "unique"

    def result(self, state):
        if len(state) > 1:
            raise ValueError(
                "More than one distinct value passed to the unique reducer"
            )
        for e in state.values():
            return e[1]
        return None


class AnyReducer(_MultisetReducer):
    name = "any"

    def result(self, state):
        if not state:
            return None
        # deterministic: smallest by hashable encoding
        h = min(state.keys(), key=lambda x: repr(x))
        return state[h][1]


class SortedTupleReducer(Reducer):
    name = "sorted_tuple"

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def init_state(self):
        return {}

    def update(self, state, value, diff, key, ts):
        if value is None and self.skip_nones:
            return state
        h = _hashable(value)
        entry = state.get(h)
        if entry is None:
            entry = [0, value]
            state[h] = entry
        entry[0] += diff
        # == 0, not <= 0: within one consolidated batch a retraction may be
        # processed before its matching insertion; negative counts must
        # persist so the insertion can cancel them
        if entry[0] == 0:
            del state[h]
        return state

    def result(self, state):
        values: List[Any] = []
        for count, value in state.values():
            values.extend([value] * max(count, 0))
        return tuple(sorted(values, key=lambda v: (v is None, v)))


class TupleReducer(Reducer):
    """Tuple ordered by row key (deterministic)."""

    name = "tuple"
    n_args = 2  # (value, order_key)

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def init_state(self):
        return {}

    def update(self, state, value, diff, key, ts):
        val, order = value
        if val is None and self.skip_nones:
            return state
        h = _hashable((order, val))
        entry = state.get(h)
        if entry is None:
            entry = [0, (order, val)]
            state[h] = entry
        entry[0] += diff
        # == 0, not <= 0: within one consolidated batch a retraction may be
        # processed before its matching insertion; negative counts must
        # persist so the insertion can cancel them
        if entry[0] == 0:
            del state[h]
        return state

    def result(self, state):
        entries = sorted(state.values(), key=lambda e: e[1][0])
        out: List[Any] = []
        for count, (order, val) in entries:
            out.extend([val] * max(count, 0))
        return tuple(out)


class AvgReducer(Reducer):
    name = "avg"

    def init_state(self):
        return (0.0, 0)

    def update(self, state, value, diff, key, ts):
        s, c = state
        return (s + value * diff, c + diff)

    def result(self, state):
        s, c = state
        return s / c if c else None

    def batch_contribs(self, args, diffs, inv, n_groups):
        v = args[0]
        if not isinstance(v, np.ndarray) or v.ndim != 1 or v.dtype == object:
            return None
        if v.dtype == np.uint64 or not (
            np.issubdtype(v.dtype, np.integer)
            or np.issubdtype(v.dtype, np.floating)
        ):
            return None
        sums = np.bincount(
            inv, weights=v.astype(np.float64) * diffs, minlength=n_groups
        )
        counts = np.bincount(inv, weights=diffs, minlength=n_groups).astype(
            np.int64
        )
        return list(zip(sums, counts))

    def merge_contrib(self, state, contrib):
        s, c = state
        ds, dc = contrib
        return (s + ds, c + int(dc))


class EarliestReducer(Reducer):
    name = "earliest"

    def init_state(self):
        return {}

    def update(self, state, value, diff, key, ts):
        h = _hashable((ts, key, value))
        entry = state.get(h)
        if entry is None:
            entry = [0, (ts, key, value)]
            state[h] = entry
        entry[0] += diff
        # == 0, not <= 0: within one consolidated batch a retraction may be
        # processed before its matching insertion; negative counts must
        # persist so the insertion can cancel them
        if entry[0] == 0:
            del state[h]
        return state

    def result(self, state):
        if not state:
            return None
        best = min(state.values(), key=lambda e: (e[1][0], e[1][1]))
        return best[1][2]


class LatestReducer(EarliestReducer):
    name = "latest"

    def result(self, state):
        if not state:
            return None
        best = max(state.values(), key=lambda e: (e[1][0], e[1][1]))
        return best[1][2]


class StatefulReducer(Reducer):
    """User combine function folded over the group's multiset IN ARRIVAL
    ORDER (reference: stateful reducers, reduce.rs:StatefulReducer &
    stateful_reduce.rs).  Retraction-safe because we re-fold on read.

    Each insertion records a per-group sequence number so interleaved
    duplicate values keep their positions (order-sensitive folds like the
    HMM/Viterbi reducer depend on it); a retraction of a value cancels its
    most recent surviving occurrence."""

    name = "stateful"

    def __init__(self, combine: Callable[[Optional[Any], List[Tuple[Any, ...]]], Any]):
        self.combine = combine

    def init_state(self):
        return {"n": 0, "items": {}}

    def update(self, state, value, diff, key, ts):
        items = state["items"]
        h = _hashable(value)
        entry = items.get(h)
        if entry is None:
            entry = [0, value, []]  # count, value, surviving arrival seqs
            items[h] = entry
        entry[0] += diff
        if diff > 0:
            entry[2].append(state["n"])
            state["n"] += 1
        elif entry[2]:
            entry[2].pop()
        # == 0, not <= 0: within one consolidated batch a retraction may be
        # processed before its matching insertion; negative counts must
        # persist so the insertion can cancel them
        if entry[0] == 0:
            del items[h]
        return state

    def result(self, state):
        ordered: List[Tuple[int, Any]] = []
        for count, value, seqs in state["items"].values():
            n = min(max(count, 0), len(seqs))
            for s in seqs[-n:] if n else []:
                ordered.append((s, value))
        if not ordered:
            return None
        ordered.sort(key=lambda p: p[0])
        return self.combine(None, [v for _, v in ordered])
