"""Source and sink operators.

Sources mirror the reference's connector sessions: a *native* session is a
stream of explicit insert/remove events, an *upsert* session keys rows and
derives retractions from the previous row for the key (reference:
src/connectors/adaptors.rs:23-80).  Connector threads push events into an
``InputSession`` buffer; the scheduler drains it once per commit tick.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...internals import dtype as dt
from ...internals.keys import KEY_DTYPE
from ..delta import Delta, as_column, empty_delta
from ..graph import EngineOperator, EngineTable, OutputCallbacks

__all__ = ["InputSession", "SourceOperator", "SubscribeOperator", "StaticSourceOperator"]

_INSERT = 0
_REMOVE = 1
_UPSERT = 2
_DELETE_BY_KEY = 3
_BATCH_MARK = 4
# one event carrying a whole COLUMNAR insert batch: (kind, n_rows,
# (keys uint64[n], {col: np.ndarray[n]})) — the bulk-ingest hot path skips
# per-row python tuples entirely (reference: connectors hand the engine
# parsed batches, not rows)
_COLUMNAR = 5


class InputSession:
    """Thread-safe buffer of input events pushed by connector threads.

    ``mark_batch()`` seals the events pushed so far into an atomic batch:
    each drain returns at most one sealed batch, so marked batches land at
    distinct commit ticks REGARDLESS of thread/scheduler timing (the
    structural analog of the reference's per-commit timestamp advancement,
    src/connectors/mod.rs commit_duration ticks)."""

    def __init__(self, upsert: bool = False, atomic_batches: bool = False):
        self._lock = threading.Lock()
        self._events: List[Tuple[int, int, Optional[Tuple[Any, ...]]]] = []
        self._since_mark = 0
        self.upsert = upsert
        # atomic mode: unsealed rows are invisible to drains until
        # mark_batch() (or close) — a mid-batch poll can never split a batch
        self.atomic_batches = atomic_batches
        self.finished = False
        self._error: Optional[BaseException] = None
        # persistence hook: called with each raw event as it is appended
        # (persistence/engine_state.py SourcePersistence.record); replayed
        # events injected via push_raw are deliberately not re-recorded
        self.recorder = None

    def insert(self, key: int, row: Tuple[Any, ...]) -> None:
        event = (_UPSERT if self.upsert else _INSERT, key, row)
        with self._lock:
            self._events.append(event)
            self._since_mark += 1
            # record under the lock: with concurrent producers the persisted
            # event order must match the in-memory order, or upsert replay
            # could resolve a key to a different last-writer
            if self.recorder is not None:
                self.recorder(event)

    def insert_batch(self, keys, rows) -> None:
        """Bulk insert: one lock acquisition and one list extend for the whole
        batch (connector readers hand over rows thousands at a time; per-row
        ``insert`` calls would serialize on the lock)."""
        kind = _UPSERT if self.upsert else _INSERT
        events = [(kind, int(k), tuple(r)) for k, r in zip(keys, rows)]
        with self._lock:
            self._events.extend(events)
            self._since_mark += len(events)
            # record under the lock: with concurrent producers the persisted
            # event order must match the in-memory order, or upsert replay
            # could resolve a key to a different last-writer
            if self.recorder is not None:
                for event in events:
                    self.recorder(event)

    def insert_columnar(self, keys, columns: Dict[str, Any]) -> None:
        """Bulk insert of a whole columnar batch as ONE event (no per-row
        tuples anywhere on the path; drains into a Delta directly).  Only
        for plain-insert streams — upsert sessions need per-row chain
        resolution."""
        if self.upsert:
            raise ValueError("insert_columnar requires a non-upsert session")
        keys = np.asarray(keys, dtype=np.uint64)
        event = (_COLUMNAR, len(keys), (keys, columns))
        with self._lock:
            self._events.append(event)
            self._since_mark += len(keys)
            if self.recorder is not None:
                self.recorder(event)

    def remove(self, key: int, row: Optional[Tuple[Any, ...]] = None) -> None:
        event = (_DELETE_BY_KEY if row is None else _REMOVE, key, row)
        with self._lock:
            self._events.append(event)
            self._since_mark += 1
            if self.recorder is not None:
                self.recorder(event)

    def mark_batch(self) -> None:
        """Seal events pushed since the previous marker into one batch."""
        event = (_BATCH_MARK, 0, None)
        with self._lock:
            if not self._since_mark:
                return
            self._events.append(event)
            self._since_mark = 0
            # markers persist with the event log so replayed atomic sources
            # reproduce their batch boundaries (and drain at all — an atomic
            # session never releases unsealed rows)
            if self.recorder is not None:
                self.recorder(event)

    def close(self) -> None:
        with self._lock:
            self.finished = True

    def fail(self, exc: BaseException) -> None:
        """A connector runner crashed: surface the exception at the next
        engine drain instead of letting the daemon thread's death read as a
        clean end-of-stream (the reference's reader-thread errors likewise
        fail the run, src/connectors/mod.rs error channel)."""
        with self._lock:
            self._error = exc
            self.finished = True

    def drain(self) -> List[Tuple[int, int, Optional[Tuple[Any, ...]]]]:
        """Take the next sealed batch, or (non-atomic / finished) the
        unsealed tail."""
        with self._lock:
            if self._error is not None:
                raise self._error
            for i, (kind, _k, _r) in enumerate(self._events):
                if kind == _BATCH_MARK:
                    events = self._events[:i]
                    self._events = self._events[i + 1 :]
                    return events
            if self.atomic_batches and not self.finished:
                return []
            events, self._events = self._events, []
            self._since_mark = 0
            return events

    def push_raw(self, events: List[Tuple[int, int, Optional[Tuple[Any, ...]]]]) -> None:
        """Inject raw events verbatim (persistence replay path)."""
        with self._lock:
            self._events.extend(events)
            # count the unsealed tail so a later mark_batch() can seal it
            self._since_mark = 0
            for kind, _k, _r in self._events:
                if kind == _BATCH_MARK:
                    self._since_mark = 0
                else:
                    self._since_mark += 1

    @property
    def has_pending(self) -> bool:
        with self._lock:
            if self._error is not None:
                return True  # force a drain so the failure surfaces
            if self.atomic_batches and not self.finished:
                return any(kind == _BATCH_MARK for kind, _k, _r in self._events)
            return bool(self._events)


class SourceOperator(EngineOperator):
    """Drains an InputSession into deltas once per tick."""

    def __init__(
        self,
        output: EngineTable,
        session: InputSession,
        dtypes: Optional[Dict[str, dt.DType]] = None,
        name: str = "source",
    ):
        super().__init__([], output, name)
        self.session = session
        self.dtypes = dtypes or {}

    @property
    def finished(self) -> bool:
        return self.session.finished and not self.session.has_pending

    def poll(self, ts: int) -> Optional[Delta]:
        return self.events_to_delta(self.session.drain())

    def events_to_delta(self, events) -> Optional[Delta]:
        """Resolve a raw event batch into a keyed delta against this
        operator's current output store (upsert chains, delete-by-key).  The
        distributed executor calls this AFTER routing raw events to their
        key owner, so resolution always sees the owner's store."""
        if not events:
            return None
        names = self.output.column_names
        store = self.output.store
        if any(e[0] == _COLUMNAR for e in events):
            if all(e[0] in (_INSERT, _COLUMNAR) for e in events):
                # pure inserts: columnar batches become Deltas verbatim, row
                # inserts batch separately; order is immaterial for +1 rows
                deltas = []
                rows_ev = [e for e in events if e[0] == _INSERT]
                if rows_ev:
                    deltas.append(self.events_to_delta(rows_ev))
                for kind, n, (keys, cols) in (
                    e for e in events if e[0] == _COLUMNAR
                ):
                    deltas.append(
                        Delta(
                            keys=np.asarray(keys, dtype=KEY_DTYPE),
                            diffs=np.ones(n, dtype=np.int64),
                            columns={
                                name: as_column(cols[name], self.dtypes.get(name))
                                for name in names
                            },
                        )
                    )
                return Delta.concat([d for d in deltas if d is not None], names)
            # mixed with upserts/removals: decompose to row events (rare)
            flat = []
            for e in events:
                if e[0] != _COLUMNAR:
                    flat.append(e)
                    continue
                _kind, n, (keys, cols) = e
                col_list = [cols[name] for name in names]
                for i in range(n):
                    flat.append(
                        (_INSERT, int(keys[i]), tuple(c[i] for c in col_list))
                    )
            events = flat
        if all(e[0] == _INSERT for e in events):
            # pure-insert batch (the bulk-ingest shape): no upsert chains to
            # resolve — build the delta columnar without the per-event loop
            columns = {}
            if names:
                transposed = list(zip(*(e[2] for e in events)))
                for ci, name in enumerate(names):
                    columns[name] = as_column(
                        list(transposed[ci]), self.dtypes.get(name)
                    )
            return Delta(
                keys=np.fromiter(
                    (e[1] for e in events), dtype=KEY_DTYPE, count=len(events)
                ),
                diffs=np.ones(len(events), dtype=np.int64),
                columns=columns,
            )
        keys: List[int] = []
        diffs: List[int] = []
        rows: List[Tuple[Any, ...]] = []
        # pending view of this batch so same-tick upsert chains resolve
        pending: Dict[int, Optional[Tuple[Any, ...]]] = {}

        def current(key: int) -> Optional[Tuple[Any, ...]]:
            if key in pending:
                return pending[key]
            return store.get(key)

        for kind, key, row in events:
            if kind == _INSERT:
                keys.append(key)
                diffs.append(1)
                rows.append(row)
                pending[key] = row
            elif kind == _REMOVE:
                keys.append(key)
                diffs.append(-1)
                rows.append(row)
                pending[key] = None
            elif kind == _UPSERT:
                old = current(key)
                if old is not None:
                    keys.append(key)
                    diffs.append(-1)
                    rows.append(old)
                keys.append(key)
                diffs.append(1)
                rows.append(row)
                pending[key] = row
            elif kind == _DELETE_BY_KEY:
                old = current(key)
                if old is not None:
                    keys.append(key)
                    diffs.append(-1)
                    rows.append(old)
                    pending[key] = None
        if not keys:
            return None
        columns = {}
        for ci, name in enumerate(names):
            columns[name] = as_column([r[ci] for r in rows], self.dtypes.get(name))
        return Delta(
            keys=np.array(keys, dtype=KEY_DTYPE),
            diffs=np.array(diffs, dtype=np.int64),
            columns=columns,
        )

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        # sources are driven by poll(), not by upstream deltas
        return delta


class StaticSourceOperator(SourceOperator):
    """A source pre-loaded with static rows, emitted once at the first tick
    (reference static_table, graph.rs:688)."""

    def __init__(
        self,
        output: EngineTable,
        keys: np.ndarray,
        columns: Dict[str, np.ndarray],
        dtypes: Optional[Dict[str, dt.DType]] = None,
        name: str = "static",
    ):
        session = InputSession()
        super().__init__(output, session, dtypes, name)
        names = output.column_names
        for i in range(len(keys)):
            session.insert(int(keys[i]), tuple(columns[c][i] for c in names))
        session.close()


class SubscribeOperator(EngineOperator):
    """Sink delivering per-row change callbacks (pw.io.subscribe;
    reference Graph::subscribe_table, graph.rs:700)."""

    def __init__(
        self,
        input_table: EngineTable,
        callbacks: OutputCallbacks,
        name: str = "subscribe",
    ):
        super().__init__([input_table], None, name)
        self.callbacks = callbacks
        self._seen_any = False

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if self.callbacks.on_change is not None:
            names = self.inputs[0].column_names
            cols = [delta.columns[c] for c in names]
            for i in range(delta.n):
                self.callbacks.on_change(
                    int(delta.keys[i]),
                    tuple(c[i] for c in cols),
                    ts,
                    int(delta.diffs[i]),
                )
        self._seen_any = self._seen_any or delta.n > 0
        return None

    def on_tick_end(self, ts: int) -> Optional[Delta]:
        if self.callbacks.on_time_end is not None:
            self.callbacks.on_time_end(ts)
        return None

    def on_end(self) -> Optional[Delta]:
        if self.callbacks.on_end is not None:
            self.callbacks.on_end()
        return None
