"""Incremental group-by/reduce operator
(reference: Graph::group_by_table, src/engine/graph.rs:885; differential
reduce per shard, src/engine/dataflow.rs).

Group key = hash of grouping values (so groups land on deterministic mesh
shards); per-group reducer state updates under insertions and retractions;
each affected group re-emits retraction of its previous output row + the new
aggregate row.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...internals.expression import ColumnExpression
from ...internals.keys import KEY_DTYPE, ref_scalars_batch
from ..delta import Delta, rows_equal
from ..graph import EngineOperator, EngineTable
from ..reducers import Reducer
from .rowwise import build_eval_context

__all__ = ["GroupByOperator", "ReducerSpec"]


class ReducerSpec:
    def __init__(
        self,
        out_name: str,
        reducer: Reducer,
        arg_expressions: Sequence[ColumnExpression],
        include_key: bool = False,
    ):
        self.out_name = out_name
        self.reducer = reducer
        self.arg_expressions = list(arg_expressions)
        self.include_key = include_key


class GroupByOperator(EngineOperator):
    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        grouping_expressions: Mapping[str, ColumnExpression],  # out col -> expr
        reducer_specs: Sequence[ReducerSpec],
        ctx_cols: Mapping[Tuple[int, str], str],
        key_expression: Optional[ColumnExpression] = None,
        name: str = "groupby",
    ):
        super().__init__([input_table], output, name)
        self.grouping_expressions = dict(grouping_expressions)
        self.reducer_specs = list(reducer_specs)
        self.ctx_cols = dict(ctx_cols)
        # groupby(id=...): group key taken directly from this pointer column
        self.key_expression = key_expression
        # group_key -> [row_count, grouping_values_tuple, [reducer states]]
        self._groups: Dict[int, List[Any]] = {}

    def dist_routing(self, port: int):
        # distributed: route input rows to the owner of their GROUP key, so
        # each rank reduces a disjoint set of groups (reference: exchange on
        # the grouping key before differential reduce, dataflow.rs
        # group_by_table)
        return self._group_keys

    def _group_keys(self, delta: Delta) -> np.ndarray:
        ctx = build_eval_context(delta, self.ctx_cols)
        if self.key_expression is not None:
            return np.asarray(self.key_expression._eval(ctx)).astype(KEY_DTYPE)
        gvals = [
            np.asarray(e._eval(ctx)) for e in self.grouping_expressions.values()
        ]
        if gvals:
            return ref_scalars_batch(gvals)
        return np.zeros(delta.n, dtype=KEY_DTYPE)

    def snapshot_state(self):
        return self._groups

    def restore_state(self, state) -> None:
        self._groups = state

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        delta = delta.consolidated()
        ctx = build_eval_context(delta, self.ctx_cols)
        group_names = list(self.grouping_expressions.keys())
        gvals = [np.asarray(self.grouping_expressions[g]._eval(ctx)) for g in group_names]
        if self.key_expression is not None:
            gkeys = np.asarray(self.key_expression._eval(ctx)).astype(KEY_DTYPE)
        elif gvals:
            gkeys = ref_scalars_batch(gvals)
        else:
            gkeys = np.zeros(delta.n, dtype=KEY_DTYPE)
        arg_arrays: List[List[np.ndarray]] = []
        for spec in self.reducer_specs:
            arg_arrays.append([np.asarray(e._eval(ctx)) for e in spec.arg_expressions])

        touched = self._update_groups_batch(delta, gkeys, gvals, arg_arrays)
        if touched is None:
            touched = self._update_groups_rowwise(
                delta, gkeys, gvals, arg_arrays, ts
            )
        return self._emit(touched, group_names)

    def _update_groups_batch(self, delta, gkeys, gvals, arg_arrays):
        """Vectorised state update: collapse the delta to one contribution
        per (group, reducer) via the additive-reducer batch interface, then
        visit only the touched groups in Python — rows never enter a Python
        loop.  Returns None when any reducer/dtype can't vectorise."""
        for spec in self.reducer_specs:
            if spec.include_key:
                return None
        uniq, first_idx, inv = np.unique(
            gkeys, return_index=True, return_inverse=True
        )
        n_groups = len(uniq)
        contribs: List[Any] = []
        for spec, args in zip(self.reducer_specs, arg_arrays):
            c = spec.reducer.batch_contribs(args, delta.diffs, inv, n_groups)
            if c is None:
                return None
            contribs.append(c)
        count_delta = np.bincount(
            inv, weights=delta.diffs, minlength=n_groups
        ).astype(np.int64)
        touched: Dict[int, None] = {}
        uniq_list = uniq.tolist()
        for j, gk in enumerate(uniq_list):
            entry = self._groups.get(gk)
            if entry is None:
                i = int(first_idx[j])
                entry = [
                    0,
                    tuple(gv[i] for gv in gvals),
                    [spec.reducer.init_state() for spec in self.reducer_specs],
                ]
                self._groups[gk] = entry
            entry[0] += int(count_delta[j])
            states = entry[2]
            for si, spec in enumerate(self.reducer_specs):
                states[si] = spec.reducer.merge_contrib(states[si], contribs[si][j])
            touched[gk] = None
        return touched

    def _update_groups_rowwise(self, delta, gkeys, gvals, arg_arrays, ts):
        touched: Dict[int, None] = {}
        for i in range(delta.n):
            gk = int(gkeys[i])
            diff = int(delta.diffs[i])
            rkey = int(delta.keys[i])
            entry = self._groups.get(gk)
            if entry is None:
                entry = [
                    0,
                    tuple(gv[i] for gv in gvals),
                    [spec.reducer.init_state() for spec in self.reducer_specs],
                ]
                self._groups[gk] = entry
            entry[0] += diff
            for si, spec in enumerate(self.reducer_specs):
                args = arg_arrays[si]
                if spec.reducer.n_args == 0:
                    value: Any = None
                elif len(args) == 1 and spec.reducer.n_args == 1:
                    value = args[0][i]
                else:
                    value = tuple(a[i] for a in args)
                if spec.include_key:
                    value = (value, rkey) if not isinstance(value, tuple) else value
                entry[2][si] = spec.reducer.update(entry[2][si], value, diff, rkey, ts)
            touched[gk] = None
        return touched

    def _emit(self, touched, group_names) -> Optional[Delta]:
        out_names = self.output.column_names
        out_rows: List[Tuple[int, int, Tuple[Any, ...]]] = []
        for gk in touched:
            entry = self._groups.get(gk)
            old = self.output.store.get(gk)
            if entry is None or entry[0] <= 0:
                self._groups.pop(gk, None)
                new_row = None
            else:
                values: Dict[str, Any] = {}
                for gi, gname in enumerate(group_names):
                    values[gname] = entry[1][gi]
                for si, spec in enumerate(self.reducer_specs):
                    values[spec.out_name] = spec.reducer.result(entry[2][si])
                new_row = tuple(values[c] for c in out_names)
            if old is not None and not rows_equal(old, new_row):
                out_rows.append((gk, -1, old))
            if new_row is not None and not rows_equal(old, new_row):
                out_rows.append((gk, 1, new_row))
        if not out_rows:
            return None
        return Delta.from_rows(out_names, out_rows)
