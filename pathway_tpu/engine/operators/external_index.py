"""External index operator: incrementally maintained retrieval index answering
query rows (reference: Graph::use_external_index_as_of_now,
src/engine/graph.rs:915; custom timely operator
src/engine/dataflow/operators/external_index.rs; framework
src/external_integration/mod.rs:40-130).

Two flavors:
- as-of-now (serving): each query answered against the index state at
  arrival; answers never retract when the index changes (matches
  ``query_as_of_now``).
- consistent: query results are maintained — when the index changes, affected
  answers are retracted and re-emitted (matches ``query()``).  Recomputation
  is batched per tick (one device matmul for all live queries), which is the
  columnar analog of differential's per-record updates.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...internals.expression import ColumnExpression
from ...internals.keys import KEY_DTYPE
from ..delta import Delta, rows_equal
from ..graph import EngineOperator, EngineTable
from .rowwise import build_eval_context

__all__ = ["ExternalIndexOperator"]


class ExternalIndexOperator(EngineOperator):
    """Inputs: port 0 = data (indexed side), port 1 = queries.

    Output columns: ``_pw_qkey`` (query key copy), ``_pw_reply`` (tuple of
    (data_key, score) pairs, best first), keyed by query key."""

    def __init__(
        self,
        data_table: EngineTable,
        query_table: EngineTable,
        output: EngineTable,
        index,  # protocol: add(keys, values, metadatas), remove(keys), search(values, k, filters)
        data_expr: ColumnExpression,
        data_ctx: Mapping[Tuple[int, str], str],
        query_expr: ColumnExpression,
        query_ctx: Mapping[Tuple[int, str], str],
        k: int = 3,
        k_expr: Optional[ColumnExpression] = None,
        metadata_expr: Optional[ColumnExpression] = None,
        filter_expr: Optional[ColumnExpression] = None,
        asof_now: bool = True,
        name: str = "external_index",
    ):
        super().__init__([data_table, query_table], output, name)
        self.index = index
        self.data_expr = data_expr
        self.data_ctx = dict(data_ctx)
        self.query_expr = query_expr
        self.query_ctx = dict(query_ctx)
        self.k = k
        self.k_expr = k_expr  # optional per-query match count column
        self.metadata_expr = metadata_expr
        self.filter_expr = filter_expr
        self.asof_now = asof_now
        # consistent mode: live queries qkey -> (value, filter, k)
        self._queries: Dict[int, Tuple[Any, Any, int]] = {}
        self._dirty = False

    def dist_routing(self, port: int):
        # distributed: every rank maintains the FULL index and sees every
        # query.  The device plane shards under the hood (DeviceKnnIndex on a
        # global mesh needs every process issuing the same jit calls — SPMD);
        # rank-0-only processing would deadlock those collectives, and
        # key-sharding host-side would duplicate what the mesh already does.
        return "replicate"

    # -- data side ---------------------------------------------------------
    def _process_data(self, delta: Delta) -> None:
        delta = delta.consolidated()
        rets = delta.retractions()
        ins = delta.insertions()
        if rets.n:
            self.index.remove([int(k) for k in rets.keys])
        if ins.n:
            ctx = build_eval_context(ins, self.data_ctx)
            values = self.data_expr._eval(ctx)
            metadatas = (
                list(self.metadata_expr._eval(ctx))
                if self.metadata_expr is not None
                else [None] * ins.n
            )
            self.index.add([int(k) for k in ins.keys], list(values), metadatas)
        self._dirty = self._dirty or delta.n > 0

    # -- query side --------------------------------------------------------
    def _answer(
        self,
        qkeys: Sequence[int],
        values: Sequence[Any],
        filters: Sequence[Any],
        ks: Optional[Sequence[int]] = None,
    ) -> List[Tuple[int, int, Tuple[Any, ...]]]:
        if ks is not None:
            ks = [int(kv) if kv is not None else self.k for kv in ks]
            k_max = max(ks) if ks else self.k
        else:
            k_max = self.k
        replies = self.index.search(list(values), k_max, list(filters))
        out = []
        for i, (qk, reply) in enumerate(zip(qkeys, replies)):
            k_i = ks[i] if ks is not None else self.k
            out.append((int(qk), 1, (np.uint64(qk), tuple(reply[:k_i]))))
        return out

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        out_names = self.output.column_names
        if port == 0:
            self._process_data(delta)
            if self.asof_now or not self._queries:
                return None
            # consistent mode: recompute all live queries, emit diffs
            qkeys = list(self._queries.keys())
            values = [self._queries[qk][0] for qk in qkeys]
            filters = [self._queries[qk][1] for qk in qkeys]
            ks = [self._queries[qk][2] for qk in qkeys]
            fresh = self._answer(qkeys, values, filters, ks)
            out: List[Tuple[int, int, Tuple[Any, ...]]] = []
            for qk, _diff, row in fresh:
                old = self.output.store.get(qk)
                if old is not None and not rows_equal(old, row):
                    out.append((qk, -1, old))
                if old is None or not rows_equal(old, row):
                    out.append((qk, 1, row))
            return Delta.from_rows(out_names, out) if out else None

        # port 1: queries
        delta = delta.consolidated()
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        rets = delta.retractions()
        for qk in rets.keys:
            qk = int(qk)
            self._queries.pop(qk, None)
            old = self.output.store.get(qk)
            if old is not None:
                out.append((qk, -1, old))
        ins = delta.insertions()
        if ins.n:
            ctx = build_eval_context(ins, self.query_ctx)
            values = list(self.query_expr._eval(ctx))
            filters = (
                list(self.filter_expr._eval(ctx))
                if self.filter_expr is not None
                else [None] * ins.n
            )
            qkeys = [int(k) for k in ins.keys]
            ks = None
            if self.k_expr is not None:
                ks = [
                    int(kv) if kv is not None else self.k
                    for kv in self.k_expr._eval(ctx)
                ]
            if not self.asof_now:
                for i, (qk, v, f) in enumerate(zip(qkeys, values, filters)):
                    self._queries[qk] = (v, f, ks[i] if ks else self.k)
            out.extend(self._answer(qkeys, values, filters, ks))
        return Delta.from_rows(out_names, out) if out else None
