"""Incremental joins.

Reference: Graph::join_tables (src/engine/graph.rs:873) over differential
arrangements; JoinType inner/left/right/outer plus the non-retracting
"asof-now" flavors used by live retrieval serving
(stdlib/indexing/data_index.py:364-441).

Bilinear-rule discipline: a delta on one side joins the *other side's own
state as of before this delta* and then updates its own side, so
dA⋈B_old + dB⋈A_new sums to exactly A_new⋈B_new − A_old⋈B_old.
Outer padding uses per-join-key match counts derived from state sizes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...internals.expression import ColumnExpression
from ...internals.keys import KEY_DTYPE, ref_scalars_batch
from ..delta import Delta, _object_array
from ..graph import EngineOperator, EngineTable
from .rowwise import build_eval_context

__all__ = ["JoinOperator", "AsofNowJoinOperator", "JoinKind"]


class JoinKind:
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


_LPAD = 0x9D39247E33776D41  # sentinels mixed into padded-row keys
_RPAD = 0x8A305F5359C24D78


# join keys reserved for None pointers: match no real row id (ids are xxh3
# of values / sequential-salted, never these constants) and differ PER SIDE
# so a None on the left never meets a None on the right
_NONE_PTR_SENTINELS = (
    np.uint64(0xFFFFFFFFFFFFFFFE),  # left
    np.uint64(0xFFFFFFFFFFFFFFFF),  # right
)




class JoinOperator(EngineOperator):
    """Output columns: ``_l_<name>`` for left columns, ``_r_<name>`` for right
    columns; unmatched sides padded with None for outer kinds."""

    def __init__(
        self,
        left: EngineTable,
        right: EngineTable,
        output: EngineTable,
        left_key_exprs: Sequence[ColumnExpression],
        right_key_exprs: Sequence[ColumnExpression],
        left_ctx_cols: Mapping[Tuple[int, str], str],
        right_ctx_cols: Mapping[Tuple[int, str], str],
        kind: str = JoinKind.INNER,
        assign_id_from: Optional[str] = None,
        exact_match: bool = False,
        warn_unmatched_left: bool = False,
        pointer_keys: Optional[bool] = None,
        name: str = "join",
    ):
        super().__init__([left, right], output, name)
        # non-optional ix: the reference raises on an unresolved pointer; the
        # incremental engine keeps the row out of the output (it may match
        # later) but warns at tick end so lookup bugs stay loud (round-1
        # advice).  Warning is deferred to on_tick_end because within a tick
        # the left delta may simply be processed before the right one.
        self.warn_unmatched_left = warn_unmatched_left
        # build-time declaration that BOTH single-key sides are pointer
        # columns (ix / id joins): the raw-uint64 key path is then used
        # unconditionally, with Nones mapped to per-side sentinels — the
        # encoding must never depend on a delta's value mix, or inserts and
        # retractions of one row could disagree on its join key
        self.pointer_keys = pointer_keys
        self._unres_left: set = set()
        self._warned_unres: set = set()
        self.left_key_exprs = list(left_key_exprs)
        self.right_key_exprs = list(right_key_exprs)
        self.left_ctx_cols = dict(left_ctx_cols)
        self.right_ctx_cols = dict(right_ctx_cols)
        self.kind = kind
        self.assign_id_from = assign_id_from
        self.left_names = list(left.column_names)
        self.right_names = list(right.column_names)
        # own per-side state: join_key -> {row_key: row_tuple}
        self._left: Dict[int, Dict[int, Tuple[Any, ...]]] = {}
        self._right: Dict[int, Dict[int, Tuple[Any, ...]]] = {}

    def dist_routing(self, port: int):
        # distributed: co-locate both sides by JOIN key so matches happen
        # rank-locally (reference: differential join's exchange pact on the
        # arrangement key)
        return lambda delta: self._join_keys(delta, port)

    def snapshot_state(self):
        return {"left": self._left, "right": self._right}

    def restore_state(self, state) -> None:
        self._left = state["left"]
        self._right = state["right"]

    # -- helpers -----------------------------------------------------------
    def _join_keys(self, delta: Delta, side: int) -> np.ndarray:
        exprs = self.left_key_exprs if side == 0 else self.right_key_exprs
        ctx_cols = self.left_ctx_cols if side == 0 else self.right_ctx_cols
        ctx = build_eval_context(delta, ctx_cols)
        if self.pointer_keys and len(exprs) == 1:
            # declared pointer join (ix / id joins, dtype-known pointer
            # columns): raw-uint64 keys, Nones -> side sentinel
            arr = np.asarray(exprs[0]._eval(ctx))
            if arr.dtype == object:
                sentinel = _NONE_PTR_SENTINELS[side]
                arr = np.array(
                    [sentinel if v is None else np.uint64(v) for v in arr],
                    dtype=np.uint64,
                )
            return arr.astype(KEY_DTYPE)
        # undeclared: ALWAYS hash — the serialization tags values by their
        # own type, so both sides agree regardless of how each delta mixes
        # Nones/uint64s (a per-delta direct-path heuristic would let one
        # row's insertion and retraction disagree on its join key)
        vals = [np.asarray(e._eval(ctx)) for e in exprs]
        return ref_scalars_batch(vals)

    def _row(self, lrow: Optional[Tuple], rrow: Optional[Tuple]) -> Tuple[Any, ...]:
        l = lrow if lrow is not None else (None,) * len(self.left_names)
        r = rrow if rrow is not None else (None,) * len(self.right_names)
        return tuple(l) + tuple(r)

    # -- columnar output assembly -----------------------------------------
    def _out_keys_batch(
        self, lkeys: List[Optional[int]], rkeys: List[Optional[int]]
    ) -> np.ndarray:
        """Batched ``_out_key`` — one ref_scalars_batch call for the whole
        output instead of one per emitted row.  Row keys hash as
        pointer-tagged uint64 columns so the batch always takes the fully
        native serialize+hash path (plain python ints ≥ 2^63 would knock the
        whole column onto the per-value fallback)."""
        a = np.fromiter(
            (k if k is not None else _LPAD for k in lkeys),
            dtype=np.uint64,
            count=len(lkeys),
        )
        b = np.fromiter(
            (k if k is not None else _RPAD for k in rkeys),
            dtype=np.uint64,
            count=len(rkeys),
        )
        hashed = ref_scalars_batch([a, b])
        if self.assign_id_from == "left":
            return np.array(
                [
                    lk if lk is not None else h
                    for lk, h in zip(lkeys, hashed.tolist())
                ],
                dtype=KEY_DTYPE,
            )
        if self.assign_id_from == "right":
            return np.array(
                [
                    rk if rk is not None else h
                    for rk, h in zip(rkeys, hashed.tolist())
                ],
                dtype=KEY_DTYPE,
            )
        return hashed

    def _assemble(
        self,
        lkeys: List[Optional[int]],
        rkeys: List[Optional[int]],
        lrows: List[Optional[Tuple]],
        rrows: List[Optional[Tuple]],
        diffs: List[int],
    ) -> Delta:
        none_l = (None,) * len(self.left_names)
        none_r = (None,) * len(self.right_names)
        lt = (
            list(zip(*(r if r is not None else none_l for r in lrows)))
            if self.left_names
            else []
        )
        rt = (
            list(zip(*(r if r is not None else none_r for r in rrows)))
            if self.right_names
            else []
        )
        nl = len(self.left_names)
        columns = {}
        for ci, name in enumerate(self.output.column_names):
            # hidden side-id columns (padded side -> None) back `left.id` /
            # `right.id` in join selects; declared last, so the positional
            # left/right mapping below is unaffected
            if name == "_pw_lid":
                columns[name] = _object_array(lkeys)
            elif name == "_pw_rid":
                columns[name] = _object_array(rkeys)
            else:
                columns[name] = _object_array(lt[ci] if ci < nl else rt[ci - nl])
        return Delta(
            keys=self._out_keys_batch(lkeys, rkeys),
            diffs=np.asarray(diffs, dtype=np.int64),
            columns=columns,
        )

    # -- processing --------------------------------------------------------
    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        delta = delta.consolidated()
        jks = self._join_keys(delta, port)
        in_names = self.left_names if port == 0 else self.right_names
        cols = [delta.columns[c] for c in in_names]
        own = self._left if port == 0 else self._right
        other = self._right if port == 0 else self._left
        pad_own = self.kind in (
            (JoinKind.LEFT, JoinKind.OUTER) if port == 0 else (JoinKind.RIGHT, JoinKind.OUTER)
        )
        pad_other = self.kind in (
            (JoinKind.RIGHT, JoinKind.OUTER) if port == 0 else (JoinKind.LEFT, JoinKind.OUTER)
        )
        left_port = port == 0

        # parallel accumulators; output columns are assembled columnar at the
        # end (C-level zip) and out keys hashed in ONE batched call — per
        # emitted row this loop only does list extends/appends
        acc_l: List[Optional[int]] = []
        acc_r: List[Optional[int]] = []
        acc_lrow: List[Optional[Tuple]] = []
        acc_rrow: List[Optional[Tuple]] = []
        acc_diff: List[int] = []

        def emit_bucket(bucket: Dict[int, Tuple], key, row, d: int) -> None:
            """All (own row × other-bucket) pairs with diff d; ``key``/``row``
            None emits the padded form of the other side's rows."""
            m = len(bucket)
            if left_port:
                acc_l.extend([key] * m)
                acc_lrow.extend([row] * m)
                acc_r.extend(bucket.keys())
                acc_rrow.extend(bucket.values())
            else:
                acc_l.extend(bucket.keys())
                acc_lrow.extend(bucket.values())
                acc_r.extend([key] * m)
                acc_rrow.extend([row] * m)
            acc_diff.extend([d] * m)

        def emit_pad_own(key, row, d: int) -> None:
            if left_port:
                acc_l.append(key)
                acc_lrow.append(row)
                acc_r.append(None)
                acc_rrow.append(None)
            else:
                acc_l.append(None)
                acc_lrow.append(None)
                acc_r.append(key)
                acc_rrow.append(row)
            acc_diff.append(d)

        row_iter = (
            zip(*(list(c) for c in cols)) if cols else iter([()] * delta.n)
        )
        for jk, key, diff, row in zip(
            jks.tolist(), delta.keys.tolist(), delta.diffs.tolist(), row_iter
        ):
            own_bucket = own.setdefault(jk, {})
            other_bucket = other.get(jk) or {}
            own_before = len(own_bucket)

            if diff > 0:
                if other_bucket:
                    emit_bucket(other_bucket, key, row, 1)
                    if pad_other and own_before == 0:
                        # other side's rows were padded; retract padded forms
                        emit_bucket(other_bucket, None, None, -1)
                    if not left_port and self.warn_unmatched_left:
                        # right insert resolved these left rows
                        self._unres_left.difference_update(other_bucket.keys())
                elif pad_own:
                    emit_pad_own(key, row, 1)
                elif left_port and self.warn_unmatched_left:
                    self._unres_left.add(key)
                own_bucket[key] = row
            else:
                if left_port and self.warn_unmatched_left:
                    self._unres_left.discard(key)
                own_bucket.pop(key, None)
                own_after = len(own_bucket)
                if (
                    not left_port
                    and self.warn_unmatched_left
                    and own_after == 0
                    and other_bucket
                ):
                    # last right row for this key retracted: the surviving
                    # left rows are unmatched again
                    self._unres_left.update(other_bucket.keys())
                if other_bucket:
                    emit_bucket(other_bucket, key, row, -1)
                    if pad_other and own_after == 0 and own_before > 0:
                        emit_bucket(other_bucket, None, None, 1)
                elif pad_own:
                    emit_pad_own(key, row, -1)
                if not own_bucket:
                    own.pop(jk, None)
        if not acc_diff:
            return None
        return self._assemble(acc_l, acc_r, acc_lrow, acc_rrow, acc_diff)

    def on_tick_end(self, ts: int):
        if self.warn_unmatched_left and self._unres_left != self._warned_unres:
            if self._unres_left:
                import logging

                logging.getLogger(__name__).warning(
                    "%s: %d row(s) currently have unresolved pointers and are "
                    "absent from the output (non-optional ix promises every "
                    "pointer resolves; pass optional=True to keep unmatched "
                    "rows with None columns)",
                    self.name,
                    len(self._unres_left),
                )
            self._warned_unres = set(self._unres_left)
        return None


class AsofNowJoinOperator(JoinOperator):
    """``join_asof_now``: each left (query) row joins the right state *as of
    arrival* and the result never retracts when the right side later changes
    (reference: the asof-now contract of query_as_of_now,
    stdlib/indexing/data_index.py:364-441; use_external_index_as_of_now,
    graph.rs:915).  Left retractions do retract previously emitted rows."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # lkey -> list of (out_key, out_row) previously emitted
        self._emitted: Dict[int, List[Tuple[int, Tuple[Any, ...]]]] = {}

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        if port == 1:
            # maintain right state only; no re-emission (asof-now contract)
            jks = self._join_keys(delta, 1)
            cols = [delta.columns[c] for c in self.right_names]
            for i in range(delta.n):
                jk = int(jks[i])
                key = int(delta.keys[i])
                if delta.diffs[i] > 0:
                    self._right.setdefault(jk, {})[key] = tuple(c[i] for c in cols)
                else:
                    bucket = self._right.get(jk)
                    if bucket is not None:
                        bucket.pop(key, None)
                        if not bucket:
                            self._right.pop(jk, None)
            return None
        delta = delta.consolidated()
        jks = self._join_keys(delta, 0)
        cols = [delta.columns[c] for c in self.left_names]
        pad_left = self.kind in (JoinKind.LEFT, JoinKind.OUTER)

        # retractions replay previously emitted rows verbatim; insertions
        # accumulate columnar (same scheme as JoinOperator.process) — the
        # per-left-key _emitted bookkeeping is filled in after the one
        # batched out-key hash
        ret_keys: List[int] = []
        ret_rows: List[Tuple[Any, ...]] = []
        acc_l: List[int] = []
        acc_r: List[Optional[int]] = []
        acc_lrow: List[Tuple] = []
        acc_rrow: List[Optional[Tuple]] = []
        emit_spans: List[Tuple[int, int, int]] = []  # (left key, start, stop)
        row_iter = (
            zip(*(list(c) for c in cols)) if cols else iter([()] * delta.n)
        )
        for jk, key, diff, row in zip(
            jks.tolist(), delta.keys.tolist(), delta.diffs.tolist(), row_iter
        ):
            if diff < 0:
                for out_key, out_row in self._emitted.pop(key, []):
                    ret_keys.append(out_key)
                    ret_rows.append(out_row)
                continue
            start = len(acc_l)
            bucket = self._right.get(jk) or {}
            if bucket:
                m = len(bucket)
                acc_l.extend([key] * m)
                acc_lrow.extend([row] * m)
                acc_r.extend(bucket.keys())
                acc_rrow.extend(bucket.values())
            elif pad_left:
                acc_l.append(key)
                acc_lrow.append(row)
                acc_r.append(None)
                acc_rrow.append(None)
            emit_spans.append((key, start, len(acc_l)))
        if not acc_l and not ret_keys:
            if emit_spans:
                # inner-join queries that matched nothing still reset their
                # emitted bookkeeping
                for key, _s, _e in emit_spans:
                    self._emitted[key] = []
            return None
        ins = (
            self._assemble(acc_l, acc_r, acc_lrow, acc_rrow, [1] * len(acc_l))
            if acc_l
            else None
        )
        if ins is not None:
            ins_keys = ins.keys.tolist()
            ins_rows = list(
                zip(*(ins.columns[c] for c in self.output.column_names))
            )
            for key, start, stop in emit_spans:
                self._emitted[key] = list(
                    zip(ins_keys[start:stop], ins_rows[start:stop])
                )
        else:
            for key, _s, _e in emit_spans:
                self._emitted[key] = []
        rets = (
            Delta(
                keys=np.asarray(ret_keys, dtype=KEY_DTYPE),
                diffs=np.full(len(ret_keys), -1, dtype=np.int64),
                columns={
                    name: _object_array(col)
                    for name, col in zip(
                        self.output.column_names,
                        zip(*ret_rows)
                        if ret_rows
                        else [[]] * len(self.output.column_names),
                    )
                },
            )
            if ret_keys
            else None
        )
        if ins is None:
            return rets
        if rets is None:
            return ins
        return Delta.concat([rets, ins], self.output.column_names)
