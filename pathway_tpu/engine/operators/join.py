"""Incremental joins.

Reference: Graph::join_tables (src/engine/graph.rs:873) over differential
arrangements; JoinType inner/left/right/outer plus the non-retracting
"asof-now" flavors used by live retrieval serving
(stdlib/indexing/data_index.py:364-441).

Bilinear-rule discipline: a delta on one side joins the *other side's own
state as of before this delta* and then updates its own side, so
dA⋈B_old + dB⋈A_new sums to exactly A_new⋈B_new − A_old⋈B_old.
Outer padding uses per-join-key match counts derived from state sizes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...internals.expression import ColumnExpression
from ...internals.keys import KEY_DTYPE, ref_scalars_batch
from ..delta import Delta
from ..graph import EngineOperator, EngineTable
from .rowwise import build_eval_context

__all__ = ["JoinOperator", "AsofNowJoinOperator", "JoinKind"]


class JoinKind:
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


_LPAD = 0x9D39247E33776D41  # sentinels mixed into padded-row keys
_RPAD = 0x8A305F5359C24D78


def _normalize_pointer_array(arr: np.ndarray) -> np.ndarray:
    """Pointer columns may flow as dense uint64 arrays or object arrays of
    np.uint64/Pointer scalars (e.g. out of groupby ``any`` reducers); collapse
    the latter to dense uint64 so id-joins take the direct-key path on both
    sides."""
    from ...internals.keys import Pointer

    if arr.dtype == object and len(arr) and all(
        isinstance(v, (np.uint64, Pointer)) for v in arr
    ):
        return arr.astype(np.uint64)
    return arr


def _out_key(lkey: Optional[int], rkey: Optional[int], assign_id_from: Optional[str]) -> int:
    if assign_id_from == "left" and lkey is not None:
        return lkey
    if assign_id_from == "right" and rkey is not None:
        return rkey
    a = lkey if lkey is not None else _LPAD
    b = rkey if rkey is not None else _RPAD
    return int(ref_scalars_batch([[a], [b]])[0])


class JoinOperator(EngineOperator):
    """Output columns: ``_l_<name>`` for left columns, ``_r_<name>`` for right
    columns; unmatched sides padded with None for outer kinds."""

    def __init__(
        self,
        left: EngineTable,
        right: EngineTable,
        output: EngineTable,
        left_key_exprs: Sequence[ColumnExpression],
        right_key_exprs: Sequence[ColumnExpression],
        left_ctx_cols: Mapping[Tuple[int, str], str],
        right_ctx_cols: Mapping[Tuple[int, str], str],
        kind: str = JoinKind.INNER,
        assign_id_from: Optional[str] = None,
        exact_match: bool = False,
        name: str = "join",
    ):
        super().__init__([left, right], output, name)
        self.left_key_exprs = list(left_key_exprs)
        self.right_key_exprs = list(right_key_exprs)
        self.left_ctx_cols = dict(left_ctx_cols)
        self.right_ctx_cols = dict(right_ctx_cols)
        self.kind = kind
        self.assign_id_from = assign_id_from
        self.left_names = list(left.column_names)
        self.right_names = list(right.column_names)
        # own per-side state: join_key -> {row_key: row_tuple}
        self._left: Dict[int, Dict[int, Tuple[Any, ...]]] = {}
        self._right: Dict[int, Dict[int, Tuple[Any, ...]]] = {}

    def snapshot_state(self):
        return {"left": self._left, "right": self._right}

    def restore_state(self, state) -> None:
        self._left = state["left"]
        self._right = state["right"]

    # -- helpers -----------------------------------------------------------
    def _join_keys(self, delta: Delta, side: int) -> np.ndarray:
        exprs = self.left_key_exprs if side == 0 else self.right_key_exprs
        ctx_cols = self.left_ctx_cols if side == 0 else self.right_ctx_cols
        ctx = build_eval_context(delta, ctx_cols)
        vals = [_normalize_pointer_array(np.asarray(e._eval(ctx))) for e in exprs]
        if len(vals) == 1 and vals[0].dtype == np.uint64:
            # joining directly on key values (id joins / ix)
            return vals[0].astype(KEY_DTYPE)
        return ref_scalars_batch(vals)

    def _row(self, lrow: Optional[Tuple], rrow: Optional[Tuple]) -> Tuple[Any, ...]:
        l = lrow if lrow is not None else (None,) * len(self.left_names)
        r = rrow if rrow is not None else (None,) * len(self.right_names)
        return tuple(l) + tuple(r)

    # -- processing --------------------------------------------------------
    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        delta = delta.consolidated()
        jks = self._join_keys(delta, port)
        in_names = self.left_names if port == 0 else self.right_names
        cols = [delta.columns[c] for c in in_names]
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        own = self._left if port == 0 else self._right
        other = self._right if port == 0 else self._left
        pad_own = self.kind in (
            (JoinKind.LEFT, JoinKind.OUTER) if port == 0 else (JoinKind.RIGHT, JoinKind.OUTER)
        )
        pad_other = self.kind in (
            (JoinKind.RIGHT, JoinKind.OUTER) if port == 0 else (JoinKind.LEFT, JoinKind.OUTER)
        )

        for i in range(delta.n):
            jk = int(jks[i])
            key = int(delta.keys[i])
            row = tuple(c[i] for c in cols)
            diff = int(delta.diffs[i])
            own_bucket = own.setdefault(jk, {})
            other_bucket = other.get(jk) or {}
            own_before = len(own_bucket)

            if diff > 0:
                for okey, orow in other_bucket.items():
                    if port == 0:
                        out.append(
                            (_out_key(key, okey, self.assign_id_from), 1, self._row(row, orow))
                        )
                    else:
                        out.append(
                            (_out_key(okey, key, self.assign_id_from), 1, self._row(orow, row))
                        )
                if pad_other and own_before == 0 and other_bucket:
                    # other side's rows were padded; retract their padded forms
                    for okey, orow in other_bucket.items():
                        if port == 0:
                            out.append(
                                (_out_key(None, okey, self.assign_id_from), -1, self._row(None, orow))
                            )
                        else:
                            out.append(
                                (_out_key(okey, None, self.assign_id_from), -1, self._row(orow, None))
                            )
                if pad_own and not other_bucket:
                    if port == 0:
                        out.append(
                            (_out_key(key, None, self.assign_id_from), 1, self._row(row, None))
                        )
                    else:
                        out.append(
                            (_out_key(None, key, self.assign_id_from), 1, self._row(None, row))
                        )
                own_bucket[key] = row
            else:
                own_bucket.pop(key, None)
                own_after = len(own_bucket)
                for okey, orow in other_bucket.items():
                    if port == 0:
                        out.append(
                            (_out_key(key, okey, self.assign_id_from), -1, self._row(row, orow))
                        )
                    else:
                        out.append(
                            (_out_key(okey, key, self.assign_id_from), -1, self._row(orow, row))
                        )
                if pad_own and not other_bucket:
                    if port == 0:
                        out.append(
                            (_out_key(key, None, self.assign_id_from), -1, self._row(row, None))
                        )
                    else:
                        out.append(
                            (_out_key(None, key, self.assign_id_from), -1, self._row(None, row))
                        )
                if pad_other and own_after == 0 and own_before > 0 and other_bucket:
                    for okey, orow in other_bucket.items():
                        if port == 0:
                            out.append(
                                (_out_key(None, okey, self.assign_id_from), 1, self._row(None, orow))
                            )
                        else:
                            out.append(
                                (_out_key(okey, None, self.assign_id_from), 1, self._row(orow, None))
                            )
                if not own_bucket:
                    own.pop(jk, None)
        if not out:
            return None
        return Delta.from_rows(self.output.column_names, out)


class AsofNowJoinOperator(JoinOperator):
    """``join_asof_now``: each left (query) row joins the right state *as of
    arrival* and the result never retracts when the right side later changes
    (reference: the asof-now contract of query_as_of_now,
    stdlib/indexing/data_index.py:364-441; use_external_index_as_of_now,
    graph.rs:915).  Left retractions do retract previously emitted rows."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # lkey -> list of (out_key, out_row) previously emitted
        self._emitted: Dict[int, List[Tuple[int, Tuple[Any, ...]]]] = {}

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        if port == 1:
            # maintain right state only; no re-emission (asof-now contract)
            jks = self._join_keys(delta, 1)
            cols = [delta.columns[c] for c in self.right_names]
            for i in range(delta.n):
                jk = int(jks[i])
                key = int(delta.keys[i])
                if delta.diffs[i] > 0:
                    self._right.setdefault(jk, {})[key] = tuple(c[i] for c in cols)
                else:
                    bucket = self._right.get(jk)
                    if bucket is not None:
                        bucket.pop(key, None)
                        if not bucket:
                            self._right.pop(jk, None)
            return None
        delta = delta.consolidated()
        jks = self._join_keys(delta, 0)
        cols = [delta.columns[c] for c in self.left_names]
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        pad_left = self.kind in (JoinKind.LEFT, JoinKind.OUTER)
        for i in range(delta.n):
            jk = int(jks[i])
            key = int(delta.keys[i])
            diff = int(delta.diffs[i])
            if diff < 0:
                for out_key, out_row in self._emitted.pop(key, []):
                    out.append((out_key, -1, out_row))
                continue
            row = tuple(c[i] for c in cols)
            emitted: List[Tuple[int, Tuple[Any, ...]]] = []
            bucket = self._right.get(jk) or {}
            if bucket:
                for rkey, rrow in bucket.items():
                    ok = _out_key(key, rkey, self.assign_id_from)
                    orow = self._row(row, rrow)
                    out.append((ok, 1, orow))
                    emitted.append((ok, orow))
            elif pad_left:
                ok = _out_key(key, None, self.assign_id_from)
                orow = self._row(row, None)
                out.append((ok, 1, orow))
                emitted.append((ok, orow))
            self._emitted[key] = emitted
        if not out:
            return None
        return Delta.from_rows(self.output.column_names, out)
