"""Time gate: buffering (delay), late-data drop (cutoff), state forgetting.

The engine analog of the reference's time-column operators — ``postpone_core``
(buffer rows until the stream clock passes their release threshold,
src/engine/dataflow/operators/time_column.rs:380), ``ignore_late`` (drop rows
whose expiry the clock already passed, :677) and ``Graph::forget/freeze``
(src/engine/graph.rs:776-812).  One operator covers all three in the
micro-batch model:

- The **clock** is the maximum time-column value seen so far (data time, not
  wall time), optionally shared between operators (interval joins share one
  clock across both inputs, like the reference's global frontier).
- **delay**: a row whose ``release`` threshold is above the clock is held in
  the buffer; buffered rows are released at tick end once the clock passes
  (and flushed unconditionally when the stream ends — reference behavior on
  input closure).
- **cutoff**: a row whose ``expire`` threshold is at or below the clock *as
  of the previous batches* is dropped (an atomic batch is never split by its
  own maximum).  Retractions targeting buffered rows cancel in place.
- **forgetting**: downstream operators register ``sweep_hooks``; each tick
  the gate calls them with a one-tick-lagged clock so a hook never forgets
  state for rows released in the same collection round.  Hooks drop expired
  group/join state (and, for keep_results=False, retract frozen results).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...internals.expression import ColumnExpression
from ..delta import Delta, _object_array
from ..graph import EngineOperator, EngineTable
from .rowwise import build_eval_context

__all__ = ["TimeGateOperator", "SharedClock"]


class SharedClock:
    """Monotone max over every time value routed through the attached gates
    (the micro-batch analog of the reference's input frontier)."""

    def __init__(self) -> None:
        self.value: float = float("-inf")

    def advance(self, t: float) -> None:
        if t > self.value:
            self.value = t


# a sweep hook takes the lagged clock and returns (table, retraction delta)
# or None; it may mutate its owner's state (forget expired groups)
SweepHook = Callable[[float], Optional[Tuple[EngineTable, Delta]]]


class TimeGateOperator(EngineOperator):
    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        time_expr: ColumnExpression,
        release_expr: Optional[ColumnExpression],
        expire_expr: Optional[ColumnExpression],
        ctx_cols,
        clock: Optional[SharedClock] = None,
        name: str = "time_gate",
    ):
        super().__init__([input_table], output, name)
        self.time_expr = time_expr
        self.release_expr = release_expr
        self.expire_expr = expire_expr
        self.ctx_cols = dict(ctx_cols)
        self.clock = clock or SharedClock()
        # key -> (row tuple, release threshold)
        self._buffer: Dict[int, Tuple[Tuple[Any, ...], float]] = {}
        self._swept_clock: float = float("-inf")
        self._prev_clock: float = float("-inf")
        self.sweep_hooks: List[SweepHook] = []

    # -- persistence -------------------------------------------------------
    def snapshot_state(self):
        return {
            "buffer": self._buffer,
            "clock": self.clock.value,
            "swept": self._swept_clock,
        }

    def restore_state(self, state) -> None:
        self._buffer = state["buffer"]
        self.clock.advance(state["clock"])
        self._swept_clock = state["swept"]
        self._prev_clock = state["clock"]

    # -- processing --------------------------------------------------------
    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        delta = delta.consolidated()
        ctx = build_eval_context(delta, self.ctx_cols)
        times = np.asarray(self.time_expr._eval(ctx), dtype=np.float64)
        releases = (
            np.asarray(self.release_expr._eval(ctx), dtype=np.float64)
            if self.release_expr is not None
            else None
        )
        expires = (
            np.asarray(self.expire_expr._eval(ctx), dtype=np.float64)
            if self.expire_expr is not None
            else None
        )
        # the cutoff comparison uses the clock BEFORE this batch: one atomic
        # batch never drops its own rows however they are ordered inside it
        clock_before = self.clock.value
        names = self.output.column_names
        cols = [delta.columns[c] for c in names]

        out_keys: List[int] = []
        out_diffs: List[int] = []
        out_rows: List[Tuple[Any, ...]] = []
        row_iter = zip(*(list(c) for c in cols)) if cols else iter([()] * delta.n)
        for i, (key, diff, row) in enumerate(
            zip(delta.keys.tolist(), delta.diffs.tolist(), row_iter)
        ):
            if diff > 0:
                self.clock.advance(float(times[i]))
                if expires is not None and float(expires[i]) <= clock_before:
                    continue  # late: dropped (ignore_late)
                if releases is not None:
                    rel = float(releases[i])
                    if rel > self.clock.value:
                        self._buffer[key] = (row, rel)
                        continue
                out_keys.append(key)
                out_diffs.append(1)
                out_rows.append(row)
            else:
                held = self._buffer.pop(key, None)
                if held is not None:
                    continue  # cancelled while still buffered
                if expires is not None and float(expires[i]) <= clock_before:
                    continue  # retraction of an already-frozen row: blocked
                out_keys.append(key)
                out_diffs.append(-1)
                out_rows.append(row)
        if not out_keys:
            return None
        return self._delta_of(out_keys, out_diffs, out_rows)

    def _delta_of(self, keys, diffs, rows) -> Delta:
        names = self.output.column_names
        transposed = list(zip(*rows)) if rows else [()] * len(names)
        return Delta(
            keys=np.asarray(keys, dtype=np.uint64),
            diffs=np.asarray(diffs, dtype=np.int64),
            columns={
                name: _object_array(transposed[ci])
                for ci, name in enumerate(names)
            },
        )

    def _release_due(self, threshold: float) -> Optional[Delta]:
        due = [
            (key, row)
            for key, (row, rel) in self._buffer.items()
            if rel <= threshold
        ]
        if not due:
            return None
        for key, _row in due:
            del self._buffer[key]
        return self._delta_of(
            [k for k, _ in due], [1] * len(due), [r for _, r in due]
        )

    def on_tick_end(self, ts: int):
        outputs: List[Tuple[EngineTable, Delta]] = []
        released = self._release_due(self.clock.value)
        if released is not None:
            outputs.append((self.output, released))
        # sweeps lag one tick so hooks never forget state belonging to rows
        # released in this same collection round (the exactly-once shape has
        # release == expire)
        sweep_clock = self._prev_clock
        self._prev_clock = self.clock.value
        if sweep_clock > self._swept_clock:
            self._swept_clock = sweep_clock
            for hook in self.sweep_hooks:
                out = hook(sweep_clock)
                if out is not None:
                    outputs.append(out)
        return outputs or None

    def on_end(self):
        # input closed: flush every buffered row (reference postpone flushes
        # on stream end); no final sweep — results stand
        released = self._release_due(float("inf"))
        return [(self.output, released)] if released is not None else None
