"""Stateless-ish per-row operators: select, filter, reindex, concat,
update_rows/cells, flatten, restrict/difference.

Retraction discipline: for an incoming retraction of key ``k`` the operator
re-emits the row it previously produced for ``k`` by looking it up in its
output table's RowStore (which the scheduler updates only *after* process
returns) — this keeps non-deterministic UDF outputs consistent, matching the
reference's arrangement-backed retraction semantics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...internals import dtype as dt
from ...internals.expression import ColumnExpression, EvalContext
from ...internals.keys import KEY_DTYPE, ref_scalars_batch
from ..delta import Delta, as_column, empty_delta, rows_equal
from ..graph import EngineOperator, EngineTable

__all__ = [
    "RowwiseOperator",
    "FilterOperator",
    "ReindexOperator",
    "ConcatOperator",
    "UpdateRowsOperator",
    "UpdateCellsOperator",
    "FlattenOperator",
    "RestrictOperator",
    "DifferenceOperator",
    "build_eval_context",
]


def build_eval_context(
    delta: Delta,
    ctx_cols: Mapping[Tuple[int, str], str],
) -> EvalContext:
    """Map API-level column references to this delta's engine columns."""
    columns = {api_ref: delta.columns[engine_col] for api_ref, engine_col in ctx_cols.items()}
    return EvalContext(columns, delta.keys)


class RowwiseOperator(EngineOperator):
    """select / with_columns: output columns are expressions over input rows
    (reference: expression_table, src/engine/graph.rs:708)."""

    def dist_routing(self, port: int):
        return None  # row-local: output key = input key, no cross-row state

    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        expressions: Dict[str, ColumnExpression],
        ctx_cols: Mapping[Tuple[int, str], str],
        dtypes: Optional[Dict[str, dt.DType]] = None,
        name: str = "select",
    ):
        super().__init__([input_table], output, name)
        self.expressions = expressions
        self.ctx_cols = dict(ctx_cols)
        self.dtypes = dtypes or {}

    def _eval_insertions(self, ins: Delta) -> Delta:
        ctx = build_eval_context(ins, self.ctx_cols)
        out_columns = {}
        for out_name, expr in self.expressions.items():
            arr = expr._eval(ctx)
            out_columns[out_name] = (
                arr if isinstance(arr, np.ndarray) else as_column(arr, self.dtypes.get(out_name))
            )
        return Delta(keys=ins.keys, diffs=ins.diffs, columns=out_columns)

    def _eval_row(self, delta: Delta, i: int) -> Tuple[Any, ...]:
        one = self._eval_insertions(delta.select_rows(np.array([i])))
        return tuple(one.columns[c][0] for c in self.output.column_names)

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        diffs = delta.diffs
        if np.all(diffs > 0):
            return self._eval_insertions(delta)
        rets = delta.retractions()
        ins = delta.insertions()
        if (
            len(np.unique(rets.keys)) == rets.n
            and len(np.unique(ins.keys)) == ins.n
        ):
            # the dominant shape: each key at most once per polarity
            # (retract-old + insert-new); deltas arrive consolidated
            # (retractions first), so store lookups pair correctly
            out_ret = self.output.store.lookup_delta(rets.keys) if rets.n else None
            out_ins = self._eval_insertions(ins) if ins.n else None
            parts = [p for p in (out_ret, out_ins) if p is not None and p.n > 0]
            if not parts:
                return None
            return Delta.concat(parts, self.output.column_names)
        # A key occurs multiple times (within-tick transient: retract+insert
        # chains).  Walk rows in order with a local view of the output so each
        # retraction pairs with exactly one prior emission — a store lookup
        # per retraction would re-emit the same stored row for every
        # occurrence and corrupt downstream aggregates.
        names = self.output.column_names
        ins_out = self._eval_insertions(ins) if ins.n else None
        ins_cols = [ins_out.columns[c] for c in names] if ins_out is not None else []
        out_rows: List[Tuple[int, int, Tuple[Any, ...]]] = []
        local: Dict[int, Optional[Tuple[Any, ...]]] = {}
        ins_ptr = 0
        for i in range(delta.n):
            key = int(delta.keys[i])
            if diffs[i] > 0:
                row = tuple(c[ins_ptr] for c in ins_cols)
                ins_ptr += 1
                out_rows.append((key, 1, row))
                local[key] = row
            else:
                if key in local:
                    prev = local[key]
                    if prev is not None:
                        out_rows.append((key, -1, prev))
                        local[key] = None
                    else:
                        out_rows.append((key, -1, self._eval_row(delta, i)))
                else:
                    stored = self.output.store.get(key)
                    if stored is not None:
                        out_rows.append((key, -1, stored))
                    else:
                        # never materialised: retract the value this row
                        # would have produced (cancels its in-flight insert)
                        out_rows.append((key, -1, self._eval_row(delta, i)))
                    local[key] = None
        if not out_rows:
            return None
        return Delta.from_rows(names, out_rows)


class FilterOperator(EngineOperator):
    """filter rows by a boolean expression (graph.rs: filter_table)."""

    def dist_routing(self, port: int):
        return None  # row-local: output key = input key, no cross-row state

    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        expression: ColumnExpression,
        ctx_cols: Mapping[Tuple[int, str], str],
        name: str = "filter",
    ):
        super().__init__([input_table], output, name)
        self.expression = expression
        self.ctx_cols = dict(ctx_cols)

    def _eval_mask(self, part: Delta) -> np.ndarray:
        ctx = build_eval_context(part, self.ctx_cols)
        mask = np.asarray(self.expression._eval(ctx))
        if mask.dtype == object:
            mask = np.array([bool(m) for m in mask], dtype=bool)
        return mask.astype(bool)

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        rets = delta.retractions()
        ins = delta.insertions()
        if rets.n == 0 or (
            len(np.unique(rets.keys)) == rets.n
            and len(np.unique(ins.keys)) == ins.n
        ):
            parts = []
            if rets.n:
                # retract only rows that previously passed the filter
                parts.append(self.output.store.lookup_delta(rets.keys))
            if ins.n:
                passed = ins.select_rows(self._eval_mask(ins))
                if passed.n:
                    parts.append(
                        Delta(
                            keys=passed.keys,
                            diffs=passed.diffs,
                            columns={c: passed.columns[c] for c in self.output.column_names},
                        )
                    )
            parts = [p for p in parts if p.n > 0]
            if not parts:
                return None
            return Delta.concat(parts, self.output.column_names)
        # repeated keys within one delta — order-preserving walk (see
        # RowwiseOperator.process) so transient retract/insert chains pair up
        names = self.output.column_names
        ins_mask = self._eval_mask(ins) if ins.n else np.empty(0, dtype=bool)
        ins_cols = [ins.columns[c] for c in names]
        out_rows: List[Tuple[int, int, Tuple[Any, ...]]] = []
        local: Dict[int, Optional[Tuple[Any, ...]]] = {}
        cols = [delta.columns[c] for c in names]
        ins_ptr = 0
        for i in range(delta.n):
            key = int(delta.keys[i])
            if delta.diffs[i] > 0:
                if ins_mask[ins_ptr]:
                    row = tuple(c[ins_ptr] for c in ins_cols)
                    out_rows.append((key, 1, row))
                    local[key] = row
                else:
                    local[key] = None
                ins_ptr += 1
            else:
                def cancel_in_flight(idx: int, k: int) -> None:
                    # no prior emission to pair with (second retraction of a
                    # delete-after-update chain, or never-materialised row):
                    # cancel the in-flight insert if the row passes the filter
                    if self._eval_mask(delta.select_rows(np.array([idx])))[0]:
                        out_rows.append((k, -1, tuple(c[idx] for c in cols)))

                if key in local:
                    prev = local[key]
                    if prev is not None:
                        out_rows.append((key, -1, prev))
                        local[key] = None
                    else:
                        cancel_in_flight(i, key)
                else:
                    stored = self.output.store.get(key)
                    if stored is not None:
                        out_rows.append((key, -1, stored))
                    else:
                        cancel_in_flight(i, key)
                    local[key] = None
        if not out_rows:
            return None
        return Delta.from_rows(names, out_rows)


class ReindexOperator(EngineOperator):
    """Rekey rows by an expression (with_id_from / reindex;
    graph.rs: reindex_table).  The new key is recomputed from row values, so
    retractions rekey consistently."""

    def dist_routing(self, port: int):
        # row-local: the new key is a pure function of the row, so insert and
        # retraction rekey identically wherever they are processed
        return None

    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        key_expression: ColumnExpression,
        ctx_cols: Mapping[Tuple[int, str], str],
        name: str = "reindex",
    ):
        super().__init__([input_table], output, name)
        self.key_expression = key_expression
        self.ctx_cols = dict(ctx_cols)

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        ctx = build_eval_context(delta, self.ctx_cols)
        new_keys = np.asarray(self.key_expression._eval(ctx)).astype(KEY_DTYPE)
        return Delta(
            keys=new_keys,
            diffs=delta.diffs,
            columns={c: delta.columns[c] for c in self.output.column_names},
        )


class ConcatOperator(EngineOperator):
    """Disjoint union of N same-schema inputs (graph.rs: concat).

    ``checked=True`` (the default without a disjointness promise) tracks
    which input each live key came from and raises on a cross-input
    collision — a silent collision would overwrite rows in the output store
    (the reference proves disjointness statically with its universe solver,
    internals/universe_solver.py; ``pw.universes.
    promise_are_pairwise_disjoint`` elides this runtime check)."""

    def dist_routing(self, port: int):
        return "key"  # co-locate ports by row key (owner = key shard)

    def __init__(
        self,
        inputs: Sequence[EngineTable],
        output: EngineTable,
        column_maps: Sequence[Mapping[str, str]],
        checked: bool = True,
        name: str = "concat",
    ):
        super().__init__(inputs, output, name)
        self.column_maps = [dict(m) for m in column_maps]
        self.checked = checked
        # per-port live-key SET + a tiny pending-retraction side dict (a
        # retraction can precede its matching insertion across deltas within
        # one tick); collision suspects are verified at tick end, because a
        # key may legitimately migrate between inputs within a tick.  All
        # bulk state updates are C-level set ops — no per-row Python loop on
        # the hot path.
        self._live: List[set] = [set() for _ in inputs]
        self._pending_neg: List[Dict[int, int]] = [{} for _ in inputs]
        self._suspects: set = set()

    def snapshot_state(self):
        return {"live": self._live, "pending": self._pending_neg}

    def restore_state(self, state) -> None:
        self._live = state["live"]
        self._pending_neg = state["pending"]

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if self.checked:
            pos = delta.diffs > 0
            inserted = set(delta.keys[pos].tolist())
            removed = set(delta.keys[~pos].tolist())
            live = self._live[port]
            pending = self._pending_neg[port]
            for key in removed - live:  # early retraction: usually empty
                pending[key] = pending.get(key, 0) + 1
            live -= removed
            if pending:
                cancelled = inserted & pending.keys()
                for key in cancelled:
                    if pending[key] == 1:
                        del pending[key]
                    else:
                        pending[key] -= 1
                inserted -= cancelled
            live |= inserted
            for other_port, other in enumerate(self._live):
                if other_port != port:
                    self._suspects |= inserted & other
        cmap = self.column_maps[port]
        return Delta(
            keys=delta.keys,
            diffs=delta.diffs,
            columns={out: delta.columns[src] for out, src in cmap.items()},
        )

    def on_tick_end(self, ts: int):
        if self._suspects:
            for key in self._suspects:
                owners = [p for p, live in enumerate(self._live) if key in live]
                if len(owners) > 1:
                    raise ValueError(
                        f"concat inputs are not disjoint: key {key:#x} is "
                        f"live in inputs {owners}; use concat_reindex, "
                        "or promise disjointness with "
                        "pw.universes.promise_are_pairwise_disjoint"
                    )
            self._suspects.clear()
        return None


class UpdateRowsOperator(EngineOperator):
    """``left.update_rows(right)``: right rows shadow left rows on key clash
    (reference: update_rows_table, graph.rs:726)."""

    def dist_routing(self, port: int):
        return "key"  # co-locate ports by row key (owner = key shard)

    def __init__(
        self,
        left: EngineTable,
        right: EngineTable,
        output: EngineTable,
        right_column_map: Mapping[str, str],
        name: str = "update_rows",
    ):
        super().__init__([left, right], output, name)
        self.right_column_map = dict(right_column_map)  # output name -> right name
        self._left: Dict[int, Tuple[Any, ...]] = {}
        self._right: Dict[int, Tuple[Any, ...]] = {}

    def _emit(self, key: int, row: Optional[Tuple[Any, ...]], out) -> None:
        old = self.output.store.get(key)
        # collect (key, diff, row) triples
        if old is not None and not rows_equal(old, row):
            out.append((key, -1, old))
        if row is not None and not rows_equal(old, row):
            out.append((key, 1, row))

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        names = self.output.column_names
        if port == 0:
            in_names = names
        else:
            in_names = [self.right_column_map[c] for c in names]
        side = self._left if port == 0 else self._right
        changed: List[Tuple[int, int, Tuple[Any, ...]]] = []
        cols = [delta.columns[c] for c in in_names]
        touched: Dict[int, None] = {}
        for i in range(delta.n):
            key = int(delta.keys[i])
            row = tuple(c[i] for c in cols)
            if delta.diffs[i] > 0:
                side[key] = row
            else:
                side.pop(key, None)
            touched[key] = None
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        for key in touched:
            effective = self._right.get(key, self._left.get(key))
            self._emit(key, effective, out)
        if not out:
            return None
        return Delta.from_rows(names, out)


class UpdateCellsOperator(EngineOperator):
    """``left.update_cells(right)``: right overrides a subset of columns for
    keys it contains (reference: update_cells_table, graph.rs:717)."""

    def dist_routing(self, port: int):
        return "key"  # co-locate ports by row key (owner = key shard)

    def __init__(
        self,
        left: EngineTable,
        right: EngineTable,
        output: EngineTable,
        updated_columns: Mapping[str, str],  # output/left name -> right name
        name: str = "update_cells",
    ):
        super().__init__([left, right], output, name)
        self.updated_columns = dict(updated_columns)
        self._left: Dict[int, Tuple[Any, ...]] = {}
        self._right: Dict[int, Tuple[Any, ...]] = {}

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        names = self.output.column_names
        touched: Dict[int, None] = {}
        if port == 0:
            cols = [delta.columns[c] for c in names]
            for i in range(delta.n):
                key = int(delta.keys[i])
                row = tuple(c[i] for c in cols)
                if delta.diffs[i] > 0:
                    self._left[key] = row
                else:
                    self._left.pop(key, None)
                touched[key] = None
        else:
            rnames = list(self.updated_columns.values())
            cols = [delta.columns[c] for c in rnames]
            for i in range(delta.n):
                key = int(delta.keys[i])
                row = tuple(c[i] for c in cols)
                if delta.diffs[i] > 0:
                    self._right[key] = row
                else:
                    self._right.pop(key, None)
                touched[key] = None
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        upd_idx = {
            left_name: ri for ri, left_name in enumerate(self.updated_columns.keys())
        }
        for key in touched:
            base = self._left.get(key)
            patch = self._right.get(key)
            if base is None:
                effective = None
            elif patch is None:
                effective = base
            else:
                effective = tuple(
                    patch[upd_idx[name]] if name in upd_idx else base[ci]
                    for ci, name in enumerate(names)
                )
            old = self.output.store.get(key)
            if old is not None and not rows_equal(old, effective):
                out.append((key, -1, old))
            if effective is not None and not rows_equal(old, effective):
                out.append((key, 1, effective))
        if not out:
            return None
        return Delta.from_rows(names, out)


class FlattenOperator(EngineOperator):
    """Explode an iterable column into one row per element; new key =
    hash(parent key, position) (reference: flatten_table, graph.rs:820)."""

    def dist_routing(self, port: int):
        return None  # row-local: output key = input key, no cross-row state

    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        flatten_column: str,
        name: str = "flatten",
    ):
        super().__init__([input_table], output, name)
        self.flatten_column = flatten_column

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        names = self.output.column_names
        src_cols = delta.columns
        out_rows: List[Tuple[int, int, Tuple[Any, ...]]] = []
        flat = src_cols[self.flatten_column]
        for i in range(delta.n):
            parent_key = int(delta.keys[i])
            diff = int(delta.diffs[i])
            seq = flat[i]
            if seq is None:
                continue
            items = list(seq) if not isinstance(seq, np.ndarray) else list(seq)
            for pos, item in enumerate(items):
                child_key = int(ref_scalars_batch([[parent_key], [pos]])[0])
                row = tuple(
                    item if c == self.flatten_column else src_cols[c][i] for c in names
                )
                out_rows.append((child_key, diff, row))
        if not out_rows:
            return None
        return Delta.from_rows(names, out_rows)


class RestrictOperator(EngineOperator):
    """Keep rows of ``data`` whose key is present in ``keyset``
    (restrict / intersect / having; graph.rs: restrict_or_override_table)."""

    def dist_routing(self, port: int):
        return "key"  # co-locate ports by row key (owner = key shard)

    def __init__(
        self,
        data: EngineTable,
        keyset: EngineTable,
        output: EngineTable,
        invert: bool = False,
        name: str = "restrict",
    ):
        super().__init__([data, keyset], output, name)
        self.invert = invert
        self._data: Dict[int, Tuple[Any, ...]] = {}
        self._keys: Dict[int, int] = {}

    def _present(self, key: int) -> bool:
        present = key in self._keys
        return (not present) if self.invert else present

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        names = self.output.column_names
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        if port == 0:
            cols = [delta.columns[c] for c in names]
            for i in range(delta.n):
                key = int(delta.keys[i])
                row = tuple(c[i] for c in cols)
                if delta.diffs[i] > 0:
                    self._data[key] = row
                    if self._present(key):
                        out.append((key, 1, row))
                else:
                    self._data.pop(key, None)
                    if self._present(key):
                        out.append((key, -1, row))
        else:
            for i in range(delta.n):
                key = int(delta.keys[i])
                if delta.diffs[i] > 0:
                    was = self._present(key)
                    self._keys[key] = self._keys.get(key, 0) + 1
                    now = self._present(key)
                else:
                    was = self._present(key)
                    cnt = self._keys.get(key, 0) - 1
                    if cnt <= 0:
                        self._keys.pop(key, None)
                    else:
                        self._keys[key] = cnt
                    now = self._present(key)
                if was != now and key in self._data:
                    out.append((key, 1 if now else -1, self._data[key]))
        if not out:
            return None
        return Delta.from_rows(names, out)


class DifferenceOperator(RestrictOperator):
    """data minus keys of other (t.difference)."""

    def __init__(self, data, keyset, output, name: str = "difference"):
        super().__init__(data, keyset, output, invert=True, name=name)
