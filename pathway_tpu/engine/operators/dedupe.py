"""Deduplicate: per-instance single accepted row chosen by a user acceptor
(reference: pw.stdlib.stateful.deduplicate, stdlib/stateful/deduplicate.py:9;
engine: deduplicate via stateful reduce, src/engine/dataflow/operators/
stateful_reduce.rs)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from ...internals.expression import ColumnExpression
from ...internals.keys import KEY_DTYPE, ref_scalars_batch
from ..delta import Delta, rows_equal
from ..graph import EngineOperator, EngineTable
from .rowwise import build_eval_context

__all__ = ["DeduplicateOperator"]


class DeduplicateOperator(EngineOperator):
    def __init__(
        self,
        input_table: EngineTable,
        output: EngineTable,
        value_expression: ColumnExpression,
        instance_expression: Optional[ColumnExpression],
        acceptor: Callable[[Any, Any], bool],
        ctx_cols: Mapping[Tuple[int, str], str],
        name: str = "deduplicate",
    ):
        super().__init__([input_table], output, name)
        self.value_expression = value_expression
        self.instance_expression = instance_expression
        self.acceptor = acceptor
        self.ctx_cols = dict(ctx_cols)
        # instance key -> (accepted value, row)
        self._state: Dict[int, Tuple[Any, Tuple[Any, ...]]] = {}

    def snapshot_state(self):
        return self._state

    def restore_state(self, state) -> None:
        self._state = state

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        ins = delta.insertions()
        if ins.n == 0:
            return None
        ctx = build_eval_context(ins, self.ctx_cols)
        values = np.asarray(self.value_expression._eval(ctx))
        if self.instance_expression is not None:
            inst_vals = np.asarray(self.instance_expression._eval(ctx))
            inst_keys = ref_scalars_batch([inst_vals])
        else:
            inst_keys = np.zeros(ins.n, dtype=KEY_DTYPE)
        names = self.output.column_names
        cols = [ins.columns[c] for c in names]
        out: List[Tuple[int, int, Tuple[Any, ...]]] = []
        for i in range(ins.n):
            ik = int(inst_keys[i])
            value = values[i]
            row = tuple(c[i] for c in cols)
            prev = self._state.get(ik)
            prev_value = prev[0] if prev is not None else None
            if prev is None or self.acceptor(value, prev_value):
                if prev is not None and not rows_equal(prev[1], row):
                    out.append((ik, -1, prev[1]))
                    out.append((ik, 1, row))
                elif prev is None:
                    out.append((ik, 1, row))
                self._state[ik] = (value, row)
        if not out:
            return None
        return Delta.from_rows(names, out)
