"""Sorted prev/next pointers — the engine op behind ``Table.sort``.

The reference computes, for every row, pointers to its predecessor and
successor in key order within an instance, incrementally via a custom timely
operator (src/engine/dataflow/operators/prev_next.rs; surfaced as
``pw.Table.sort``, python/pathway/internals/table.py:2157).  Here the
operator keeps one bisect-sorted order per instance and on each delta
re-links the touched instances, emitting only rows whose (prev, next) pair
actually changed — the incremental output matches a from-scratch sort.
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..delta import Delta
from ..graph import EngineOperator, EngineTable

__all__ = ["SortOperator"]


def _hashable(value: Any) -> Any:
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, (list, np.ndarray)):
        return tuple(value)
    return value


class SortOperator(EngineOperator):
    """Input columns: ``_pw_sort_key`` (orderable) and ``_pw_instance``;
    output columns ``prev``/``next`` (uint64 pointers or None), keyed by the
    input row keys.  Ties order by row key, so the order is deterministic."""

    def __init__(self, input: EngineTable, output: EngineTable, name: str = "sort"):
        super().__init__([input], output, name)
        # instance -> sorted [(sort_key, row_key), ...]
        self._orders: Dict[Any, List[Tuple[Any, int]]] = {}
        # row_key -> (prev_key | None, next_key | None)
        self._links: Dict[int, Tuple[Optional[int], Optional[int]]] = {}

    def snapshot_state(self):
        return {"orders": self._orders, "links": self._links}

    def restore_state(self, state) -> None:
        self._orders = state["orders"]
        self._links = state["links"]

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n == 0:
            return None
        delta = delta.consolidated()
        kcol = list(delta.columns["_pw_sort_key"])
        icol = list(delta.columns["_pw_instance"])
        # neighbour-local incremental maintenance: each mutation touches at
        # most itself and its two adjacent entries, so only those rows are
        # re-linked afterwards — O(batch * log n), never a full-order rescan
        affected: Dict[Any, set] = {}
        removed: List[int] = []
        for key, diff, kv, inst in zip(
            delta.keys.tolist(), delta.diffs.tolist(), kcol, icol
        ):
            inst = _hashable(inst)
            entry = (_hashable(kv), int(key))
            order = self._orders.setdefault(inst, [])
            touched = affected.setdefault(inst, set())
            if diff > 0:
                i = bisect.bisect_left(order, entry)
                order.insert(i, entry)
                touched.add(entry)
                if i > 0:
                    touched.add(order[i - 1])
                if i + 1 < len(order):
                    touched.add(order[i + 1])
            else:
                i = bisect.bisect_left(order, entry)
                if i < len(order) and order[i] == entry:
                    order.pop(i)
                    if i > 0:
                        touched.add(order[i - 1])
                    if i < len(order):
                        touched.add(order[i])
                touched.discard(entry)
                removed.append(int(key))
                if not order:
                    del self._orders[inst]

        rows: List[Tuple[int, int, Tuple[Any, Any]]] = []

        def as_ptr(k: Optional[int]):
            return np.uint64(k) if k is not None else None

        for key in removed:
            old = self._links.pop(key, None)
            if old is not None:
                rows.append((key, -1, (as_ptr(old[0]), as_ptr(old[1]))))
        for inst, touched in affected.items():
            order = self._orders.get(inst, [])
            last = len(order) - 1
            for entry in touched:
                i = bisect.bisect_left(order, entry)
                if i > last or order[i] != entry:
                    continue  # removed later in the same batch
                row_key = entry[1]
                link = (
                    order[i - 1][1] if i > 0 else None,
                    order[i + 1][1] if i < last else None,
                )
                old = self._links.get(row_key)
                if old == link:
                    continue
                if old is not None:
                    rows.append((row_key, -1, (as_ptr(old[0]), as_ptr(old[1]))))
                self._links[row_key] = link
                rows.append((row_key, 1, (as_ptr(link[0]), as_ptr(link[1]))))
        if not rows:
            return None
        return Delta.from_rows(["prev", "next"], rows)
