"""Bench-trajectory comparator: ``python -m pathway_tpu.bench_compare
BENCH_*.json`` (ISSUE 12 satellite).

``bench.py`` writes one versioned record per round (``BENCH_12.json``,
``BENCH_13.json``, …).  This module diffs consecutive records and flags
any metric that REGRESSED by more than the threshold (default 10%,
``--threshold``), so a perf cliff between rounds is a red exit code in
the next session instead of an unnoticed drift.

Metric direction is inferred from the name — the repo-wide naming
convention every bench extra already follows:

- lower-is-better: ``*_ms``, ``*_seconds``, latency percentiles
  (``p50``/``p95``/``p99``), ``*_overhead_pct``, ``*_agreement_pct``,
  anything spelled ``latency``/``lag``/``wait``;
- higher-is-better: ``*_per_s(ec)``, ``qps``, ``*_speedup*``,
  ``accuracy``, ``mrr``, ``*_rate`` (hit/dedup rates),
  ``*_reduction_x``, ``compression``, ``vs_baseline``;
- everything else (counts, byte sizes, configuration echoes) is
  reported as informational and never flagged.

Exit code: 0 = no regressions, 1 = at least one flagged regression,
2 = usage error (no/unreadable records).
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["compare_records", "direction_of", "flatten_metrics", "main"]

_LOWER_RE = re.compile(
    r"(_ms$|_ms_|_seconds$|(^|_)p(50|95|99)(_|$)|overhead|latency|lag"
    r"|_wait|agreement_pct|abs_err|drops?(_|$)|dropped|failures?(_|$)"
    r"|_errors?(_|$))"
)
_HIGHER_RE = re.compile(
    r"(per_s(ec)?$|per_sec_|qps|speedup|accuracy|(^|_)mrr|_rate$|_ratio$"
    r"|reduction|compression|vs_baseline|fraction$|tokens_per)"
)


def direction_of(name: str) -> Optional[str]:
    """'lower' / 'higher' / None (informational) for one metric name."""
    n = name.lower()
    if _LOWER_RE.search(n):
        return "lower"
    if _HIGHER_RE.search(n):
        return "higher"
    return None


def flatten_metrics(record: Dict[str, Any]) -> Dict[str, float]:
    """Every numeric leaf of a bench record, dotted-flattened
    (``extras.serve_cache.qps`` style).  Non-numeric leaves, nulls, and
    bookkeeping keys are skipped."""
    skip = {"schema", "round", "created_unix", "elapsed_s", "partial"}
    out: Dict[str, float] = {}

    def walk(prefix: str, value: Any) -> None:
        if isinstance(value, dict):
            for k, v in value.items():
                if not prefix and k in skip:
                    continue
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(value, bool):
            return
        elif isinstance(value, (int, float)) and math.isfinite(value):
            out[prefix] = float(value)

    walk("", record)
    return out


def compare_records(
    older: Dict[str, Any],
    newer: Dict[str, Any],
    threshold: float = 0.10,
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """(regressions, improvements) between two records: metrics present
    in both, with a direction, whose relative change crosses
    ``threshold`` the wrong / right way."""
    a = flatten_metrics(older)
    b = flatten_metrics(newer)
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    for name in sorted(set(a) & set(b)):
        direction = direction_of(name)
        if direction is None:
            continue
        old, new = a[name], b[name]
        if old == 0.0:
            continue  # no meaningful relative change from a zero base
        change = (new - old) / abs(old)
        worse = change > 0 if direction == "lower" else change < 0
        row = {
            "metric": name,
            "direction": direction,
            "old": old,
            "new": new,
            "change_pct": round(change * 100.0, 2),
        }
        if abs(change) <= threshold:
            continue
        (regressions if worse else improvements).append(row)
    return regressions, improvements


def _round_key(record: Dict[str, Any], path: str) -> Tuple[int, str]:
    rnd = record.get("round")
    if isinstance(rnd, int):
        return (rnd, path)
    m = re.search(r"(\d+)", str(rnd) if rnd is not None else path)
    return (int(m.group(1)) if m else 0, path)


def _usage_error(message: str) -> SystemExit:
    """Exit 2 (usage error) — distinct from exit 1 (flagged regression),
    so a CI gate never misreads a mistyped path as a perf cliff."""
    print(f"bench_compare: {message}", file=sys.stderr)
    return SystemExit(2)


def _load(paths: List[str]) -> Iterator[Tuple[str, Dict[str, Any]]]:
    for path in paths:
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            raise _usage_error(f"cannot read {path}: {exc}")
        if not isinstance(doc, dict):
            raise _usage_error(f"{path} is not a record object")
        yield path, doc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.bench_compare",
        description=(
            "Diff versioned bench records (BENCH_*.json) and flag "
            "metric regressions beyond the threshold."
        ),
    )
    parser.add_argument("records", nargs="+", help="BENCH_*.json paths")
    parser.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="relative-change flag threshold (default 0.10 = 10%%)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    args = parser.parse_args(argv)

    loaded = sorted(
        _load(args.records), key=lambda kv: _round_key(kv[1], kv[0])
    )
    if len(loaded) < 2:
        path, doc = loaded[0]
        n = len(flatten_metrics(doc))
        print(
            f"bench_compare: 1 record ({path}, round "
            f"{doc.get('round', '?')}, {n} numeric metrics) — trajectory "
            "seeded; comparisons start with the next round's record."
        )
        return 0

    any_regression = False
    report = []
    for (path_a, a), (path_b, b) in zip(loaded, loaded[1:]):
        regressions, improvements = compare_records(
            a, b, threshold=args.threshold
        )
        any_regression = any_regression or bool(regressions)
        report.append(
            {
                "older": path_a,
                "newer": path_b,
                "regressions": regressions,
                "improvements": improvements,
            }
        )
        if args.json:
            continue
        print(f"{path_a} -> {path_b}:")
        if not regressions and not improvements:
            print(
                f"  no metric moved more than {args.threshold:.0%} "
                "in either direction"
            )
        for row in regressions:
            print(
                f"  REGRESSION {row['metric']}: {row['old']:g} -> "
                f"{row['new']:g} ({row['change_pct']:+.1f}%, "
                f"{row['direction']}-is-better)"
            )
        for row in improvements:
            print(
                f"  improved   {row['metric']}: {row['old']:g} -> "
                f"{row['new']:g} ({row['change_pct']:+.1f}%)"
            )
    if args.json:
        print(json.dumps({"comparisons": report}, indent=1))
    return 1 if any_regression else 0


if __name__ == "__main__":
    sys.exit(main())
