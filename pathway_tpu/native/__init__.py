"""ctypes bindings for the native C++ runtime (``native/`` at the repo root).

The reference implements its host-side hot loops — connector scanners/parsers,
value serialization for key hashing, snapshot framing, shard routing — in Rust
(src/connectors/, src/engine/value.rs, src/persistence/); here they live in
C++ built to ``libpathway_native.so`` and loaded through ctypes.  Everything
degrades gracefully: if the library is missing and cannot be built (or
``PATHWAY_TPU_DISABLE_NATIVE=1``), pure-Python fallbacks with identical
semantics take over — tests assert native/fallback agreement bit-for-bit.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import config

__all__ = [
    "available",
    "lib",
    "build",
    "csv_scan",
    "csv_unescape",
    "parse_int64",
    "parse_float64",
    "serialize_rows",
    "hash_rows",
    "crc32",
    "frame_scan",
    "shard_rows",
    "tokenize_hash",
]

_REPO_ROOT = Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_SO_PATH = _NATIVE_DIR / "build" / "libpathway_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64 = ctypes.c_int64
_u64 = ctypes.c_uint64
_u32 = ctypes.c_uint32
_i32 = ctypes.c_int32
_u8 = ctypes.c_uint8
_p_u8 = ctypes.POINTER(_u8)
_p_i64 = ctypes.POINTER(_i64)
_p_u64 = ctypes.POINTER(_u64)


def _sources_newer_than_so() -> bool:
    if not _SO_PATH.exists():
        return True
    so_mtime = _SO_PATH.stat().st_mtime
    for src in list((_NATIVE_DIR / "src").glob("*.cc")) + list(
        (_NATIVE_DIR / "include").glob("*.h")
    ):
        if src.stat().st_mtime > so_mtime:
            return True
    return False


def build(force: bool = False) -> bool:
    """Build libpathway_native.so (make, falling back to a direct g++ call).
    Returns True if the library exists afterwards."""
    if not _NATIVE_DIR.exists():
        return False
    if not force and not _sources_newer_than_so():
        return True
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            (_NATIVE_DIR / "build").mkdir(exist_ok=True)
            srcs = sorted(str(p) for p in (_NATIVE_DIR / "src").glob("*.cc"))
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-std=c++17", "-shared", *srcs,
                 "-o", str(_SO_PATH)],
                cwd=_NATIVE_DIR,
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return False
    return _SO_PATH.exists()


def _declare(dll: ctypes.CDLL) -> ctypes.CDLL:
    dll.pn_abi_version.restype = _i64
    dll.pn_csv_count.restype = _i32
    dll.pn_csv_count.argtypes = [_p_u8, _i64, _u8, _u8, _p_i64, _p_i64]
    dll.pn_csv_scan.restype = _i32
    dll.pn_csv_scan.argtypes = [_p_u8, _i64, _u8, _u8, _p_i64, _p_i64, _p_i64, _p_u8]
    dll.pn_csv_unescape.restype = _i64
    dll.pn_csv_unescape.argtypes = [_p_u8, _i64, _u8, _p_u8]
    dll.pn_parse_int64.restype = None
    dll.pn_parse_int64.argtypes = [_p_u8, _p_i64, _p_i64, _i64, _p_i64, _p_u8]
    dll.pn_parse_float64.restype = None
    dll.pn_parse_float64.argtypes = [
        _p_u8, _p_i64, _p_i64, _i64, ctypes.POINTER(ctypes.c_double), _p_u8,
    ]
    dll.pn_serialize_rows.restype = _i64
    dll.pn_serialize_rows.argtypes = [
        _i64, _i32, _p_u8,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        _p_u8, _i64, _p_i64,
    ]
    try:
        dll.pn_hash_rows.restype = _i32
        dll.pn_hash_rows.argtypes = [_p_u8, _i64, _p_i64, _i64, _p_u64]
    except AttributeError:
        pass  # stale .so without the hashing entry point
    dll.pn_crc32.restype = _u32
    dll.pn_crc32.argtypes = [_p_u8, _i64, _u32]
    dll.pn_frame_scan.restype = _i64
    dll.pn_frame_scan.argtypes = [_p_u8, _i64, _p_i64, _p_i64, _i64, _p_i64]
    dll.pn_shard_rows.restype = None
    dll.pn_shard_rows.argtypes = [_p_u64, _i64, _u32, _u64, _p_i64, _p_i64]
    try:
        dll.pn_tokenize_hash.restype = _i32
        dll.pn_tokenize_hash.argtypes = [
            _p_u8, _p_i64, _i64, _i32, _i32, ctypes.POINTER(_i32), _p_i64,
        ]
    except AttributeError:
        pass  # stale .so without the tokenizer entry point
    return dll


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if disabled
    or unbuildable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if config.get("native.disable"):
            return None
        if not build():
            return None
        try:
            _lib = _declare(ctypes.CDLL(str(_SO_PATH)))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return lib() is not None


def _as_u8_ptr(buf: bytes):
    return ctypes.cast(ctypes.c_char_p(buf), _p_u8)


def _np_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------- CSV


def csv_scan(
    data: bytes, delim: str = ",", quote: str = '"'
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Scan a CSV byte buffer into columnar extents:
    (row_cell_start[n_rows+1], cell_off, cell_len, cell_quoted)."""
    dll = lib()
    if dll is None:
        from . import fallback

        return fallback.csv_scan(data, delim, quote)
    d, q = ord(delim), ord(quote)
    n_rows = _i64(0)
    n_cells = _i64(0)
    buf = _as_u8_ptr(data)
    dll.pn_csv_count(buf, len(data), d, q, ctypes.byref(n_rows), ctypes.byref(n_cells))
    rcs = np.empty(n_rows.value + 1, dtype=np.int64)
    off = np.empty(n_cells.value, dtype=np.int64)
    ln = np.empty(n_cells.value, dtype=np.int64)
    quoted = np.empty(n_cells.value, dtype=np.uint8)
    dll.pn_csv_scan(
        buf, len(data), d, q,
        _np_ptr(rcs, _i64), _np_ptr(off, _i64), _np_ptr(ln, _i64), _np_ptr(quoted, _u8),
    )
    return rcs, off, ln, quoted


def _py_csv_unescape(cell: bytes, qb: bytes) -> bytes:
    """Mirror of pn_csv_unescape: '""' -> '"' inside the quoted body; the lone
    closing quote is dropped and the tail after it is copied verbatim."""
    out = bytearray()
    in_quotes = True
    i, n = 0, len(cell)
    while i < n:
        c = cell[i : i + 1]
        if in_quotes and c == qb:
            if cell[i + 1 : i + 2] == qb:
                out += qb
                i += 2
                continue
            in_quotes = False
            i += 1
        else:
            out += c
            i += 1
    return bytes(out)


def csv_unescape(cell: bytes, quote: str = '"') -> bytes:
    dll = lib()
    if dll is None:
        return _py_csv_unescape(cell, quote.encode())
    out = ctypes.create_string_buffer(len(cell))
    n = dll.pn_csv_unescape(
        _as_u8_ptr(cell), len(cell), ord(quote), ctypes.cast(out, _p_u8)
    )
    return out.raw[:n]


def csv_rows(data: bytes, delim: str = ",", quote: str = '"') -> List[List[str]]:
    """Decode a CSV buffer into rows of str (skipping zero-cell rows)."""
    rcs, off, ln, quoted = csv_scan(data, delim, quote)
    qb = quote.encode()
    rows: List[List[str]] = []
    for r in range(len(rcs) - 1):
        lo, hi = rcs[r], rcs[r + 1]
        if lo == hi:
            continue
        row = []
        for c in range(lo, hi):
            cell = data[off[c] : off[c] + ln[c]]
            if quoted[c] and qb in cell:
                cell = _py_csv_unescape(cell, qb)
            row.append(cell.decode("utf-8", errors="replace"))
        rows.append(row)
    return rows


# ---------------------------------------------------------------- typed parse


def parse_int64(
    data: bytes, off: np.ndarray, ln: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    dll = lib()
    if dll is None:
        from . import fallback

        return fallback.parse_int64(data, off, ln)
    n = len(off)
    out = np.empty(n, dtype=np.int64)
    ok = np.empty(n, dtype=np.uint8)
    off = np.ascontiguousarray(off, dtype=np.int64)
    ln = np.ascontiguousarray(ln, dtype=np.int64)
    dll.pn_parse_int64(
        _as_u8_ptr(data), _np_ptr(off, _i64), _np_ptr(ln, _i64), n,
        _np_ptr(out, _i64), _np_ptr(ok, _u8),
    )
    return out, ok


def parse_float64(
    data: bytes, off: np.ndarray, ln: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    dll = lib()
    if dll is None:
        from . import fallback

        return fallback.parse_float64(data, off, ln)
    n = len(off)
    out = np.empty(n, dtype=np.float64)
    ok = np.empty(n, dtype=np.uint8)
    off = np.ascontiguousarray(off, dtype=np.int64)
    ln = np.ascontiguousarray(ln, dtype=np.int64)
    dll.pn_parse_float64(
        _as_u8_ptr(data), _np_ptr(off, _i64), _np_ptr(ln, _i64), n,
        _np_ptr(out, ctypes.c_double), _np_ptr(ok, _u8),
    )
    return out, ok


# ---------------------------------------------------------------- serialize

# column type tags shared with native/src/serialize.cc
COL_NONE, COL_BOOL, COL_INT64, COL_FLOAT64, COL_STR, COL_BYTES, COL_POINTER = range(7)


def serialize_rows(
    n_rows: int,
    col_types: Sequence[int],
    col_arrays: Sequence[object],
    col_nulls: Sequence[Optional[np.ndarray]],
) -> Tuple[bytes, np.ndarray]:
    """Serialize typed columns into per-row key-derivation buffers.

    ``col_arrays[c]``: np.int64/float64/uint8/uint64 array, or
    ``(blob: bytes, offsets: np.int64[n_rows+1])`` for str/bytes columns.
    Returns (buffer, row_offsets[n_rows+1]) matching
    internals.keys._serialize_value byte-for-byte."""
    dll = lib()
    if dll is None:
        from . import fallback

        return fallback.serialize_rows(n_rows, col_types, col_arrays, col_nulls)
    n_cols = len(col_types)
    types = np.asarray(col_types, dtype=np.uint8)
    data_ptrs = (ctypes.c_void_p * n_cols)()
    off_ptrs = (ctypes.c_void_p * n_cols)()
    null_ptrs = (ctypes.c_void_p * n_cols)()
    keepalive = []
    for c, t in enumerate(col_types):
        if t in (COL_STR, COL_BYTES):
            blob, offs = col_arrays[c]
            offs = np.ascontiguousarray(offs, dtype=np.int64)
            keepalive.append((blob, offs))
            data_ptrs[c] = ctypes.cast(ctypes.c_char_p(blob), ctypes.c_void_p)
            off_ptrs[c] = ctypes.c_void_p(offs.ctypes.data)
        elif t == COL_NONE:
            data_ptrs[c] = None
            off_ptrs[c] = None
        else:
            arr = np.ascontiguousarray(col_arrays[c])
            keepalive.append(arr)
            data_ptrs[c] = ctypes.c_void_p(arr.ctypes.data)
            off_ptrs[c] = None
        mask = col_nulls[c] if col_nulls else None
        if mask is not None:
            mask = np.ascontiguousarray(mask, dtype=np.uint8)
            keepalive.append(mask)
            null_ptrs[c] = ctypes.c_void_p(mask.ctypes.data)
        else:
            null_ptrs[c] = None
    row_offsets = np.empty(n_rows + 1, dtype=np.int64)
    needed = dll.pn_serialize_rows(
        n_rows, n_cols, _np_ptr(types, _u8),
        data_ptrs, off_ptrs, null_ptrs,
        ctypes.cast(None, _p_u8), 0, _np_ptr(row_offsets, _i64),
    )
    out = ctypes.create_string_buffer(max(int(needed), 1))
    dll.pn_serialize_rows(
        n_rows, n_cols, _np_ptr(types, _u8),
        data_ptrs, off_ptrs, null_ptrs,
        ctypes.cast(out, _p_u8), needed, _np_ptr(row_offsets, _i64),
    )
    return out.raw[:needed], row_offsets


def hash_rows(buf: bytes, row_offsets: np.ndarray) -> Optional[np.ndarray]:
    """xxh3-64 of each serialized row slice (the pn_serialize_rows layout);
    None when the library is absent or was built without xxhash — callers
    hash row-by-row in Python instead (internals/keys.ref_scalars_batch)."""
    dll = lib()
    if dll is None or not hasattr(dll, "pn_hash_rows"):
        return None
    n = len(row_offsets) - 1
    offs = np.ascontiguousarray(row_offsets, dtype=np.int64)
    out = np.empty(n, dtype=np.uint64)
    rc = dll.pn_hash_rows(
        _as_u8_ptr(buf), len(buf), _np_ptr(offs, _i64), n, _np_ptr(out, _u64)
    )
    if rc != 0:
        return None
    return out


# ---------------------------------------------------------------- crc / frames


def crc32(data: bytes, value: int = 0) -> int:
    dll = lib()
    if dll is None:
        import zlib

        return zlib.crc32(data, value) & 0xFFFFFFFF
    return int(dll.pn_crc32(_as_u8_ptr(data), len(data), value & 0xFFFFFFFF))


def frame_scan(data: bytes) -> Tuple[np.ndarray, np.ndarray, int]:
    """Scan concatenated [len][crc][payload] frames; returns
    (payload_offsets, payload_lengths, consumed_bytes) of the valid prefix."""
    dll = lib()
    if dll is None:
        from . import fallback

        return fallback.frame_scan(data)
    max_frames = max(len(data) // 8, 1)
    offs = np.empty(max_frames, dtype=np.int64)
    lens = np.empty(max_frames, dtype=np.int64)
    consumed = _i64(0)
    n = dll.pn_frame_scan(
        _as_u8_ptr(data), len(data), _np_ptr(offs, _i64), _np_ptr(lens, _i64),
        max_frames, ctypes.byref(consumed),
    )
    return offs[:n].copy(), lens[:n].copy(), consumed.value


# ---------------------------------------------------------------- sharding


def shard_rows(
    keys: np.ndarray, n_shards: int, shard_mask: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(counts[n_shards], order[n]) — stable grouping of row indices by
    shard(key) = (key & mask) % n_shards."""
    dll = lib()
    if dll is None:
        from . import fallback

        return fallback.shard_rows(keys, n_shards, shard_mask)
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    counts = np.empty(n_shards, dtype=np.int64)
    order = np.empty(len(keys), dtype=np.int64)
    dll.pn_shard_rows(
        _np_ptr(keys, _u64), len(keys), n_shards, shard_mask,
        _np_ptr(counts, _i64), _np_ptr(order, _i64),
    )
    return counts, order


# ---------------------------------------------------------------- tokenizer


def tokenize_hash(
    blob: bytes, offsets: np.ndarray, vocab_size: int, reserved: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Batch hashing tokenizer over concatenated ASCII texts
    (models/tokenizer.py semantics): returns (ids ragged int32, tok_offsets
    int64[n+1]), or None when the native path is unavailable (caller keeps
    the Python tokenizer)."""
    dll = lib()
    if dll is None or not hasattr(dll, "pn_tokenize_hash"):
        return None
    n_texts = len(offsets) - 1
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    out_ids = np.empty(max(len(blob), 1), dtype=np.int32)
    out_offsets = np.empty(n_texts + 1, dtype=np.int64)
    rc = dll.pn_tokenize_hash(
        _as_u8_ptr(blob), _np_ptr(offsets, _i64), n_texts,
        vocab_size, reserved, _np_ptr(out_ids, _i32), _np_ptr(out_offsets, _i64),
    )
    if rc != 0:
        return None
    return out_ids[: out_offsets[n_texts]], out_offsets
