"""Pure-Python fallbacks for the native library — semantics identical to the
C++ implementations in native/src/ (tests assert bit-for-bit agreement)."""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np


def csv_scan(data: bytes, delim: str = ",", quote: str = '"'):
    d, q = delim.encode(), quote.encode()
    n = len(data)
    row_cell_start: List[int] = [0]
    off: List[int] = []
    ln: List[int] = []
    quoted: List[int] = []
    i = 0
    while i < n:
        ch = data[i : i + 1]
        if ch == b"\n":
            row_cell_start.append(len(off))
            i += 1
            continue
        if ch == b"\r" and data[i + 1 : i + 2] == b"\n":
            row_cell_start.append(len(off))
            i += 2
            continue
        row_open = True
        while row_open:
            if i < n and data[i : i + 1] == q:
                i += 1
                start = i
                while i < n:
                    if data[i : i + 1] == q:
                        if data[i + 1 : i + 2] == q:
                            i += 2
                            continue
                        break
                    i += 1
                body_end = i
                if i < n:
                    i += 1
                tail_start = i
                while i < n and data[i : i + 1] not in (d, b"\n", b"\r"):
                    i += 1
                # post-quote tail kept verbatim (python csv semantics): the
                # extent then spans body + closing quote + tail
                off.append(start)
                ln.append((body_end - start) if i == tail_start else (i - start))
                quoted.append(1)
            else:
                start = i
                while i < n and data[i : i + 1] not in (d, b"\n", b"\r"):
                    i += 1
                off.append(start)
                ln.append(i - start)
                quoted.append(0)
            if i >= n:
                row_cell_start.append(len(off))
                row_open = False
            elif data[i : i + 1] == d:
                i += 1
                if i >= n:
                    off.append(n)
                    ln.append(0)
                    quoted.append(0)
                    row_cell_start.append(len(off))
                    row_open = False
            elif data[i : i + 1] == b"\n":
                i += 1
                row_cell_start.append(len(off))
                row_open = False
            else:  # \r
                i += 1
                if i < n and data[i : i + 1] == b"\n":
                    i += 1
                row_cell_start.append(len(off))
                row_open = False
    return (
        np.asarray(row_cell_start, dtype=np.int64),
        np.asarray(off, dtype=np.int64),
        np.asarray(ln, dtype=np.int64),
        np.asarray(quoted, dtype=np.uint8),
    )


def parse_int64(data: bytes, off, ln) -> Tuple[np.ndarray, np.ndarray]:
    n = len(off)
    out = np.zeros(n, dtype=np.int64)
    ok = np.zeros(n, dtype=np.uint8)
    for i in range(n):
        s = data[off[i] : off[i] + ln[i]].strip()
        try:
            v = int(s)
        except ValueError:
            continue
        if -(1 << 63) <= v < (1 << 63):
            out[i] = v
            ok[i] = 1
    return out, ok


def parse_float64(data: bytes, off, ln) -> Tuple[np.ndarray, np.ndarray]:
    n = len(off)
    out = np.full(n, np.nan, dtype=np.float64)
    ok = np.zeros(n, dtype=np.uint8)
    for i in range(n):
        s = data[off[i] : off[i] + ln[i]].strip()
        if not s:
            continue
        try:
            out[i] = float(s)
            ok[i] = 1
        except ValueError:
            pass
    return out, ok


def serialize_rows(
    n_rows: int,
    col_types: Sequence[int],
    col_arrays: Sequence[object],
    col_nulls: Sequence[Optional[np.ndarray]],
) -> Tuple[bytes, np.ndarray]:
    from . import COL_BOOL, COL_BYTES, COL_FLOAT64, COL_INT64, COL_NONE, COL_POINTER, COL_STR

    out = bytearray()
    row_offsets = np.empty(n_rows + 1, dtype=np.int64)
    row_offsets[0] = 0
    for r in range(n_rows):
        for c, t in enumerate(col_types):
            mask = col_nulls[c] if col_nulls else None
            if (mask is not None and mask[r]) or t == COL_NONE:
                out += b"\x00"
            elif t == COL_BOOL:
                out += b"\x01" + (b"\x01" if col_arrays[c][r] else b"\x00")
            elif t == COL_INT64:
                out += b"\x02" + struct.pack("<q", int(col_arrays[c][r]))
            elif t == COL_FLOAT64:
                out += b"\x03" + struct.pack("<d", float(col_arrays[c][r]))
            elif t == COL_POINTER:
                out += b"\x06" + struct.pack("<Q", int(col_arrays[c][r]))
            elif t in (COL_STR, COL_BYTES):
                blob, offs = col_arrays[c]
                cell = blob[offs[r] : offs[r + 1]]
                tag = b"\x04" if t == COL_STR else b"\x05"
                out += tag + struct.pack("<I", len(cell)) + cell
        row_offsets[r + 1] = len(out)
    return bytes(out), row_offsets


def frame_scan(data: bytes) -> Tuple[np.ndarray, np.ndarray, int]:
    offs: List[int] = []
    lens: List[int] = []
    pos = 0
    n = len(data)
    while pos + 8 <= n:
        (payload_len, crc) = struct.unpack_from("<II", data, pos)
        if pos + 8 + payload_len > n:
            break
        payload = data[pos + 8 : pos + 8 + payload_len]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        offs.append(pos + 8)
        lens.append(payload_len)
        pos += 8 + payload_len
    return (
        np.asarray(offs, dtype=np.int64),
        np.asarray(lens, dtype=np.int64),
        pos,
    )


def shard_rows(keys, n_shards: int, shard_mask: int):
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    shards = (keys & np.uint64(shard_mask)) % np.uint64(n_shards)
    counts = np.bincount(shards.astype(np.int64), minlength=n_shards).astype(np.int64)
    order = np.argsort(shards, kind="stable").astype(np.int64)
    return counts, order
