"""Device-resident forward index: compressed per-document token
representations, computed once at ingest, gathered at serve time.

The stage-2 cross-encoder re-encodes every candidate document on every
request even though documents never change between requests — rerank
FLOPs scale with document length x over-fetch (ROADMAP item 2).  The
forward-index architecture ("Efficient Neural Ranking using Forward
Indexes and Lightweight Encoders", arxiv 2311.01263; KaLM-Reranker-V1's
compressed-document reranking, arxiv 2606.22807) moves the doc-side
encode to ingest:

- **ingest (absorb)**: the doc-side encoder exports per-token hidden
  states (``SentenceEncoder.encode_token_states``); they are pooled to a
  FIXED row budget ``T'`` per document (contiguous chunk means, so the
  pad mask is a simple ``t < nvalid`` test) and int8-quantized with
  per-channel scales — HBM stays bounded and measurable
  (``pathway_forward_hbm_bytes`` / ``_compression_ratio`` gauges);
- **storage**: padded row buckets ``[capacity, T', d]`` int8 +
  ``[capacity, d]`` f32 scales + ``[capacity]`` valid-row counts, all
  HBM-resident alongside the IVF shards, capacity grown in doubling
  steps so the gather kernel holds a handful of compile shapes;
- **serve (gather)**: candidates' rows are gathered by slot, dequantized
  and MaxSim-scored against the stage-1 query token states in ONE fused
  dispatch (ops/maxsim.py) — the cross-encoder becomes an optional
  high-precision stage over only the top few.

Concurrency mirrors ``ops/ivf.py``'s absorb/commit discipline exactly:
the expensive plan (encoder dispatch + pool/quantize) runs OFF the index
lock so serving continues throughout; only the donated scatter + host
bookkeeping take the lock, with staleness guards for keys that mutated
while the plan ran.  The donated buffers force the serve-path gather to
launch before unlocking, the same launch-before-unlock rule the IVF
dispatch follows.

Failure policy (the ``robust`` ladder): a failed ingest pass is logged
once, counted on ``pathway_forward_absorb_failures_total{site=...}``,
and drops its documents from the FORWARD index only — retrieval and the
cross-encoder fallback still see them, and a serve whose gather finds
nothing degrades to the previous stage's scores flagged
``late_interaction_skipped`` (never an exception out of serve).  Chaos
sites: ``forward.absorb`` (plan), ``forward.upload`` (commit scatter),
``forward.gather`` (serve gather dispatch).
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config, observe
from ..observe import hbm, profile
from ..ops import donation_guard
from ..ops.dispatch_counter import record_dispatch, record_fetch
from ..ops.maxsim import (
    build_maxsim_kernel,
    build_maxsim_table_kernel,
    build_table_merge_kernel,
)
from ..ops.recompile_guard import RecompileTripwire
from ..robust import RetryPolicy, inject, log_once, retry_call

__all__ = [
    "ForwardIndex",
    "ForwardUnavailable",
    "ShardedForwardIndex",
    "forward_quant_mode",
    "forward_tokens_per_doc",
]

# serve-path gather retries fast and briefly: the dispatch launches
# while HOLDING the index lock (donated absorb buffers force
# launch-before-unlock, like the IVF dispatch), so the whole retry
# budget must stay in the low milliseconds
_GATHER_RETRY = RetryPolicy(attempts=3, base_delay_s=0.002, max_delay_s=0.02)

# maintenance-duration histograms (flight recorder): absorb wall time is
# the whole plan+commit pass, upload is the locked device-scatter part
_H_ABSORB = observe.histogram("pathway_forward_absorb_seconds")
_H_UPLOAD = observe.histogram("pathway_forward_upload_seconds")

# every Nth successful absorb re-measures quantization error on a
# sampled audit batch (the pathway_forward_quant_abs_err gauge)
_AUDIT_EVERY = 8


def forward_tokens_per_doc(default: int = 16) -> int:
    """Pooled doc-row budget ``T'`` from ``PATHWAY_FORWARD_TOKENS``.
    Every stored document occupies exactly ``T'`` rows (fewer real
    tokens leave trailing rows invalid), so HBM per doc is a constant
    ``T' * d`` int8 + ``d`` f32 scales."""
    return config.get("forward.tokens", fallback=default)


def forward_quant_mode(default: str = "int8") -> str:
    """``PATHWAY_FORWARD_QUANT``: ``int8`` (per-channel scales, 4x
    smaller than f32) or ``none`` (float32 rows, the parity oracle)."""
    return config.get("forward.quant", fallback=default)


class ForwardUnavailable(RuntimeError):
    """The forward index cannot serve this gather (empty, or no
    candidate is resident) — the rerank stage converts this into the
    ``late_interaction_skipped`` rung."""


@partial(
    donation_guard.donating_jit,
    site="forward.absorb_scatter",
    donate_argnums=(0, 1, 2),
)
def _forward_scatter(tok, scales, nvalid, slots, q, s, nv):
    """Scatter one absorb plan into the row buckets; donated buffers so
    XLA updates the (possibly GB-scale) token store in place.  Pad plan
    rows carry an out-of-range slot and drop.  Compiled through the
    donation tripwire (``PATHWAY_DONATION_GUARD=1`` poisons the donated
    refs post-call — ops/donation_guard.py)."""
    tok = tok.at[slots].set(q, mode="drop")
    scales = scales.at[slots].set(s, mode="drop")
    nvalid = nvalid.at[slots].set(nv, mode="drop")
    return tok, scales, nvalid


class ForwardIndex:
    """HBM-resident compressed forward index over a ``SentenceEncoder``.

    ``add(keys, texts)`` ingests (plan off-lock, commit locked);
    ``gather_submit(...)`` is the serve-path entry the late-interaction
    rerank stage drives (ops/retrieve_rerank.py).  ``tokens_per_doc``
    and ``quant`` default to the ``PATHWAY_FORWARD_TOKENS`` /
    ``PATHWAY_FORWARD_QUANT`` env knobs."""

    def __init__(
        self,
        encoder,
        tokens_per_doc: Optional[int] = None,
        quant: Optional[str] = None,
        initial_capacity: int = 1024,
    ):
        self.encoder = encoder
        self.tokens_per_doc = tokens_per_doc or forward_tokens_per_doc()
        self.quant = quant if quant in ("int8", "none") else forward_quant_mode()
        self.dimension = int(encoder.config.d_model)
        self._lock = threading.RLock()
        self._capacity = 0
        self._initial_capacity = max(64, int(initial_capacity))
        # device row buckets (allocated on first absorb): tok [cap, T', d]
        # int8 (or f32 with quant="none"), scales [cap, d] f32, nvalid
        # [cap] int32 (0 = empty/removed slot)
        self._tok: Any = None
        self._scales: Any = None
        self._nvalid: Any = None
        # host bookkeeping: key <-> slot, freed slots for reuse, per-slot
        # REAL ingest token counts (for the compression-ratio gauge)
        self._slot_of_key: Dict[int, int] = {}
        self._free: List[int] = []
        self._next_slot = 0
        # staleness guard (the IVF object-identity trick, adapted for
        # text-keyed rows): every commit/remove of a key bumps its
        # version; an off-lock plan snapshots versions at add() entry and
        # the commit drops keys that mutated while the plan ran — a
        # remove() must not be resurrected and a newer upsert must not be
        # overwritten by an older plan that committed later
        self._key_version: Dict[int, int] = {}
        self._ntok_by_slot: Optional[np.ndarray] = None
        self._nvalid_host: Optional[np.ndarray] = None
        self._tokens_stored = 0  # sum of live nvalid (pooled rows)
        self._raw_tokens_live = 0  # sum of live REAL ingest token counts
        # bumped whenever the device buffers are REPLACED (growth or
        # donated scatter): an off-lock consumer holding old refs must
        # not mix them with new bookkeeping
        self.generation = 0
        self._fns: Dict[Tuple, Any] = {}
        self._tripwire = RecompileTripwire("ForwardIndex")
        self._quant_abs_err: Optional[float] = None
        self.stats = {
            "absorbs": 0,
            "docs_absorbed": 0,
            "absorb_failures": 0,
            "upload_failures": 0,
            "gathers": 0,
            "gather_candidates": 0,
            "gather_missing": 0,
        }
        self._observe_id = observe.next_id()
        observe.register_provider(self)
        # HBM ledger (observe/hbm.py): the row buckets' allocated bytes,
        # plus capacity-exhaustion tracking from the observed absorb rate
        hbm.track("forward", self, lambda ix: {"rows": ix.hbm_bytes()})
        hbm.track_resource(
            "forward_rows",
            self,
            lambda ix: len(ix),
            lambda ix: ix._tok.shape[0] if ix._tok is not None else 0,
        )

    # -- introspection ------------------------------------------------------
    def __len__(self) -> int:
        return len(self._slot_of_key)

    def __contains__(self, key: int) -> bool:
        return int(key) in self._slot_of_key

    def hbm_bytes(self) -> int:
        """Bytes resident on device for the row buckets (allocated
        capacity, the number HBM planning cares about)."""
        total = 0
        for buf in (self._tok, self._scales, self._nvalid):
            if buf is not None:
                total += int(np.prod(buf.shape)) * buf.dtype.itemsize
        return total

    def compression_ratio(self) -> float:
        """Raw float32 token-state bytes of the LIVE documents divided
        by their stored bytes — the measurable compression the pooling +
        quantization buys (>= ~8x at T'=16/int8 on typical corpora)."""
        n = len(self._slot_of_key)
        if n == 0:
            return 1.0
        raw = self._raw_tokens_live * self.dimension * 4
        itemsize = 1 if self.quant == "int8" else 4
        stored = n * (
            self.tokens_per_doc * self.dimension * itemsize
            + self.dimension * 4
            + 4
        )
        return raw / max(stored, 1)

    # -- compiled fns -------------------------------------------------------
    def _pool_fn(self, B: int, L: int):
        """Compiled ingest compressor: ``(tokens [B, L, d] f32, mask
        [B, L]) -> (q rows, scales, nvalid, pooled_f32)``.  Fixed-budget
        pooling: the real tokens of each doc are split into ``T'``
        CONTIGUOUS chunks and mean-pooled (so valid rows are exactly
        ``0..min(T', len)-1`` and the serve kernel's ``t < nvalid`` mask
        is correct), each pooled row L2-normalized; quantization is
        per-channel symmetric int8 with the absmax scale stored."""
        T = self.tokens_per_doc
        quant = self.quant == "int8"
        key = ("pool", B, L, T, quant)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)

        @jax.jit
        def fn(tokens, mask):
            m = mask.astype(jnp.float32)
            lens = jnp.sum(m, axis=1)  # [B]
            pos = jnp.cumsum(m, axis=1) - 1.0
            # chunk id: contiguous 0..min(T, len)-1 over the real tokens
            denom = jnp.maximum(lens, float(T))[:, None]
            seg = jnp.floor(pos * T / denom)
            seg = jnp.where(m > 0, seg, float(T))  # pad -> out of range
            onehot = (
                seg[:, :, None] == jnp.arange(T)[None, None, :]
            ).astype(jnp.float32)  # [B, L, T]
            summed = jnp.einsum("blt,bld->btd", onehot, tokens)
            counts = jnp.sum(onehot, axis=1)  # [B, T]
            pooled = summed / jnp.maximum(counts, 1.0)[:, :, None]
            pooled = pooled / jnp.maximum(
                jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
            )
            valid = counts > 0
            pooled = pooled * valid[:, :, None]
            nvalid = jnp.minimum(lens, float(T)).astype(jnp.int32)
            if quant:
                absmax = jnp.max(jnp.abs(pooled), axis=1)  # [B, d]
                scales = jnp.where(absmax > 0, absmax / 127.0, 1.0)
                q = jnp.clip(
                    jnp.round(pooled / scales[:, None, :]), -127, 127
                ).astype(jnp.int8)
            else:
                scales = jnp.ones(
                    (pooled.shape[0], pooled.shape[2]), jnp.float32
                )
                q = pooled
            return q, scales, nvalid, pooled

        fn = profile.wrap("forward.pool", fn)
        self._fns[key] = fn
        return fn

    def _audit_fn(self, B: int):
        """Compiled quantization audit: mean |MaxSim(float) -
        MaxSim(dequantized)| with the first few docs' own pooled rows as
        probe queries — the ``pathway_forward_quant_abs_err`` gauge."""
        T = self.tokens_per_doc
        quant = self.quant == "int8"
        nq = min(4, B)
        key = ("audit", B, T, quant)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)

        @jax.jit
        def fn(pooled, q, scales, nvalid):
            deq = q.astype(jnp.float32)
            if quant:
                deq = deq * scales[:, None, :]
            probe = pooled[:nq]  # [nq, T, d] — probe queries
            pmask = (
                jnp.arange(T)[None, :] < nvalid[:nq, None]
            ).astype(jnp.float32)  # [nq, T] valid probe tokens
            # doc-row validity broadcast over [nq, K, Lq, T]
            tmask = (
                jnp.arange(T)[None, :] < nvalid[:, None]
            )[None, :, None, :]

            def maxsim(docs):
                sim = jnp.einsum("qld,ktd->qklt", probe, docs)
                sim = jnp.where(tmask, sim, -jnp.inf)
                best = jnp.max(sim, axis=3)  # [nq, K, Lq]
                best = jnp.where(pmask[:, None, :] > 0, best, 0.0)
                return jnp.sum(best, axis=2)

            sf = maxsim(pooled)
            sq = maxsim(deq)
            both = jnp.isfinite(sf) & jnp.isfinite(sq)
            diff = jnp.where(both, jnp.abs(sf - sq), 0.0)
            return jnp.sum(diff) / jnp.maximum(jnp.sum(both), 1)

        fn = profile.wrap("forward.audit", fn)
        self._fns[key] = fn
        return fn

    def _maxsim_fn(self, B: int, Lq: int, Kc: int, k_out: int):
        """Compiled serve gather (ops/maxsim.py), cached per shape —
        capacity and the row budget are compile dimensions, so the key
        includes them and the tripwire counts every signature."""
        key = ("maxsim", B, Lq, Kc, k_out, self._capacity, self.tokens_per_doc)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        self._tripwire.observe(key)
        fn = build_maxsim_kernel(
            B, Lq, Kc, self.tokens_per_doc, k_out, self.quant == "int8"
        )
        # device-time attribution (observe/profile.py)
        fn = profile.wrap("forward.maxsim", fn)
        self._fns[key] = fn
        return fn

    # -- ingest (absorb) ----------------------------------------------------
    def add(self, keys: Sequence[int], texts: Sequence[str]) -> int:
        """Ingest documents: encode + pool + quantize OFF the lock (the
        plan — serving continues throughout), then commit the donated
        scatter + bookkeeping under the lock, IVF-style.  Upserts
        overwrite in place; returns the number of documents committed.
        This is the live-ingest runner's (serve/ingest.py) forward-side
        absorb target: the runner fires ``ingest.commit`` upstream of
        this call, while ``forward.absorb``/``forward.upload`` below
        cover the plan and scatter independently.

        Degrade-not-die: a failed pass is logged once and counted on
        ``pathway_forward_absorb_failures_total`` — the documents simply
        stay out of the forward index (retrieval and the cross-encoder
        fallback still see them) until a later ``add`` retries."""
        keys = [int(k) for k in keys]
        if not keys:
            return 0
        t0 = time.perf_counter_ns()
        with self._lock:
            versions = {k: self._key_version.get(k, 0) for k in keys}
        try:
            plan = self._plan_absorb(keys, texts)
            plan["versions"] = versions
        except Exception as exc:
            with self._lock:
                self.stats["absorb_failures"] += 1
            log_once(
                f"forward.absorb:{type(exc).__name__}",
                "forward-index absorb plan failed (%r); documents stay "
                "out of the forward index (late-interaction degrades, "
                "serving continues) — counted on "
                "pathway_forward_absorb_failures_total",
                exc,
            )
            return 0
        try:
            with self._lock:
                n = self._commit_absorb(plan)
        except Exception as exc:
            with self._lock:
                self.stats["upload_failures"] += 1
                self.stats["absorb_failures"] += 1
            log_once(
                f"forward.upload:{type(exc).__name__}",
                "forward-index commit upload failed (%r); documents stay "
                "out of the forward index — counted on "
                "pathway_forward_absorb_failures_total",
                exc,
            )
            return 0
        _H_ABSORB.observe_ns(time.perf_counter_ns() - t0)
        if plan["audit"] is not None:
            # audit fetch OFF the lock (maintenance path): one scalar
            self._quant_abs_err = float(np.asarray(plan["audit"]))
        return n

    def _plan_absorb(self, keys: List[int], texts: Sequence[str]) -> Dict[str, Any]:
        """Encode + pool + quantize for one ingest batch.  Lock-free:
        touches only its arguments (the expensive encoder dispatch and
        the pooled/quantized device arrays live here)."""
        inject.fire("forward.absorb")  # chaos site: the off-lock plan
        tokens, mask, n = self.encoder.encode_token_states(texts)
        fn = self._pool_fn(tokens.shape[0], tokens.shape[1])
        # pathway: allow(recompile-hazard): shapes bucketed upstream — encode_token_states pads the batch with _bucket and pins L to max_len, so the pool fn compiles once per batch bucket
        q, scales, nvalid, pooled = fn(tokens, jnp.asarray(mask))
        audit = None
        if self.stats["absorbs"] % _AUDIT_EVERY == 0:
            audit = self._audit_fn(tokens.shape[0])(pooled, q, scales, nvalid)
        # real ingest token counts per doc (compression-ratio accounting)
        ntok = np.asarray(mask).sum(axis=1).astype(np.int64)[:n]
        return {
            "keys": keys,
            "n": n,
            "q": q,
            "scales": scales,
            "nvalid": nvalid,
            "ntok": ntok,
            "audit": audit,
        }

    def _commit_absorb(self, plan: Dict[str, Any]) -> int:
        """Install one absorb plan (caller holds the lock): slot
        assignment, capacity growth in doubling steps, ONE donated
        device scatter, host bookkeeping.  ``forward.upload`` is the
        chaos site for the device part."""
        keys = plan["keys"]
        n = plan["n"]
        versions = plan["versions"]
        b = int(plan["q"].shape[0])  # bucketed plan rows
        # slot per real row: upsert reuses, else free list, else fresh.
        # STALENESS GUARD: a key whose version moved while the plan ran
        # off-lock (a remove(), or a newer add() that committed first)
        # keeps slot -1 and its rows DROP below — the plan's data is
        # older than the index's current truth for that key.
        slots = np.full(b, -1, np.int64)
        fresh_needed = 0
        popped: List[int] = []  # free-list pops, rolled back on failure
        for i, key in enumerate(keys[:n]):
            if self._key_version.get(key, 0) != versions.get(key, 0):
                continue  # stale: dropped
            slot = self._slot_of_key.get(key)
            if slot is None:
                if self._free:
                    slot = self._free.pop()
                    popped.append(slot)
                else:
                    slot = self._next_slot + fresh_needed
                    fresh_needed += 1
            slots[i] = slot
        live_rows = np.flatnonzero(slots[:n] >= 0)
        if live_rows.size == 0:
            self._free.extend(popped)
            return 0  # everything went stale while the plan ran
        high = self._next_slot + fresh_needed
        try:
            self._grow_to(high)
            inject.fire("forward.upload")  # chaos site: the locked scatter
            # stale rows AND pad plan rows scatter out-of-range and drop
            slots[slots < 0] = self._capacity
            t0 = time.perf_counter_ns()
            # pathway: allow(recompile-hazard): slots share the plan's bucketed row count (stale/pad rows scatter out-of-range and drop) and capacity doubles — a handful of shapes over any ingest history
            self._tok, self._scales, self._nvalid = _forward_scatter(
                self._tok,
                self._scales,
                self._nvalid,
                jnp.asarray(slots, jnp.int32),
                plan["q"],
                plan["scales"],
                plan["nvalid"],
            )
        except BaseException:
            # a failed upload must not leak the popped free slots —
            # repeated failures would otherwise force spurious capacity
            # doublings of the GB-scale token store
            self._free.extend(popped)
            raise
        _H_UPLOAD.observe_ns(time.perf_counter_ns() - t0)
        # bookkeeping AFTER the device update succeeded: a failed scatter
        # must not leave keys mapped to slots holding stale rows
        nvalid_host = np.asarray(plan["nvalid"])[:n]
        for i in live_rows.tolist():
            key = keys[i]
            slot = int(slots[i])
            old = self._slot_of_key.get(key)
            if old is not None:
                if old == slot:
                    # in-place upsert: retire the old row's accounting
                    self._tokens_stored -= int(self._nvalid_host[slot])
                    self._raw_tokens_live -= int(self._ntok_by_slot[slot])
                else:
                    # duplicate key within one batch took a second slot:
                    # the earlier one is released for reuse
                    self._release_slot(old)
            self._slot_of_key[key] = slot
            self._key_version[key] = self._key_version.get(key, 0) + 1
            self._ntok_by_slot[slot] = plan["ntok"][i]
            self._raw_tokens_live += int(plan["ntok"][i])
            self._tokens_stored += int(nvalid_host[i])
            self._nvalid_host[slot] = int(nvalid_host[i])
        self._next_slot = max(self._next_slot, high)
        self.generation += 1
        self.stats["absorbs"] += 1
        self.stats["docs_absorbed"] += int(live_rows.size)
        return int(live_rows.size)

    def _release_slot(self, slot: int) -> None:
        """Retire one live slot's accounting and free it for reuse
        (caller holds the lock)."""
        self._tokens_stored -= int(self._nvalid_host[slot])
        self._raw_tokens_live -= int(self._ntok_by_slot[slot])
        self._ntok_by_slot[slot] = 0
        self._nvalid_host[slot] = 0
        self._free.append(slot)

    def _grow_to(self, needed_slots: int) -> None:
        """Ensure device capacity for ``needed_slots`` rows (caller
        holds the lock): capacities double from ``initial_capacity`` so
        the gather kernel sees a handful of compile shapes over any
        ingest history.  Growth is functional (concatenate) — old
        buffer refs snapshotted by an in-flight gather stay valid."""
        if needed_slots <= self._capacity:
            return
        new_cap = max(self._initial_capacity, 1)
        while new_cap < needed_slots:
            new_cap *= 2
        T, d = self.tokens_per_doc, self.dimension
        tok_dtype = jnp.int8 if self.quant == "int8" else jnp.float32
        if self._tok is None:
            self._tok = jnp.zeros((new_cap, T, d), tok_dtype)
            self._scales = jnp.ones((new_cap, d), jnp.float32)
            self._nvalid = jnp.zeros((new_cap,), jnp.int32)
            self._ntok_by_slot = np.zeros(new_cap, np.int64)
            self._nvalid_host = np.zeros(new_cap, np.int32)
        else:
            extra = new_cap - self._capacity
            self._tok = jnp.concatenate(
                [self._tok, jnp.zeros((extra, T, d), tok_dtype)]
            )
            self._scales = jnp.concatenate(
                [self._scales, jnp.ones((extra, d), jnp.float32)]
            )
            self._nvalid = jnp.concatenate(
                [self._nvalid, jnp.zeros((extra,), jnp.int32)]
            )
            self._ntok_by_slot = np.concatenate(
                [self._ntok_by_slot, np.zeros(extra, np.int64)]
            )
            self._nvalid_host = np.concatenate(
                [self._nvalid_host, np.zeros(extra, np.int32)]
            )
        self._capacity = new_cap
        self.generation += 1

    def remove(self, keys: Sequence[int]) -> None:
        """Drop documents from the forward index (host bookkeeping only:
        an unmapped slot is unreachable by any future gather, and its
        rows are overwritten when the slot is reused)."""
        with self._lock:
            for k in keys:
                k = int(k)
                # version bump regardless of residency: an in-flight
                # off-lock absorb plan for this key must not resurrect it
                self._key_version[k] = self._key_version.get(k, 0) + 1
                slot = self._slot_of_key.pop(k, None)
                if slot is not None:
                    self._release_slot(slot)

    # -- durable warm state (serve/warmstate.py) -----------------------------
    def warm_state(self) -> Dict[str, Any]:
        """Snapshot the compressed row buckets + host bookkeeping so a
        restored replica gathers bit-identically to this index.  Refs are
        captured under the lock; the device→host fetch happens OFF the
        lock (the absorb scatter is functional, so snapshotted refs stay
        valid even if a commit lands mid-fetch)."""
        with self._lock:
            tok, scales, nvalid = self._tok, self._scales, self._nvalid
            state: Dict[str, Any] = {
                "kind": "forward",
                "dimension": int(self.dimension),
                "tokens_per_doc": int(self.tokens_per_doc),
                "quant": self.quant,
                "capacity": int(self._capacity),
                "slot_of_key": dict(self._slot_of_key),
                "free": list(self._free),
                "next_slot": int(self._next_slot),
                "key_version": dict(self._key_version),
                "ntok_by_slot": (
                    None if self._ntok_by_slot is None
                    else np.array(self._ntok_by_slot)
                ),
                "nvalid_host": (
                    None if self._nvalid_host is None
                    else np.array(self._nvalid_host)
                ),
                "tokens_stored": int(self._tokens_stored),
                "raw_tokens_live": int(self._raw_tokens_live),
                "generation": int(self.generation),
            }
        state["tok"] = None if tok is None else np.asarray(tok)
        state["scales"] = None if scales is None else np.asarray(scales)
        state["nvalid"] = None if nvalid is None else np.asarray(nvalid)
        return state

    def load_warm_state(self, state: Dict[str, Any]) -> None:
        """Install a ``warm_state()`` snapshot (replica bring-up).  The
        uploads run OFF the lock; the locked install is a pointer swap,
        so an in-flight gather finishes against the old buckets.  Raises
        ``ValueError`` on a geometry/quant mismatch — the warm-state
        manager degrades that to a counted cold start, never a wrong
        index.  The restored ``generation`` matches the writer's, so
        cache/dedup keys agree across the fabric."""
        if state.get("kind") != "forward":
            raise ValueError(
                f"not a forward warm state: {state.get('kind')!r}"
            )
        for field in ("dimension", "tokens_per_doc"):
            if int(state[field]) != int(getattr(self, field)):
                raise ValueError(
                    f"{field} mismatch: snapshot {state[field]} "
                    f"vs index {getattr(self, field)}"
                )
        if state["quant"] != self.quant:
            raise ValueError(
                f"quant mismatch: snapshot {state['quant']!r} "
                f"vs index {self.quant!r}"
            )
        tok = None if state["tok"] is None else jnp.asarray(state["tok"])
        scales = (
            None if state["scales"] is None else jnp.asarray(state["scales"])
        )
        nvalid = (
            None if state["nvalid"] is None else jnp.asarray(state["nvalid"])
        )
        with self._lock:
            self._tok = tok
            self._scales = scales
            self._nvalid = nvalid
            self._capacity = int(state["capacity"])
            self._slot_of_key = {
                int(k): int(s) for k, s in state["slot_of_key"].items()
            }
            self._free = [int(s) for s in state["free"]]
            self._next_slot = int(state["next_slot"])
            self._key_version = {
                int(k): int(v) for k, v in state["key_version"].items()
            }
            self._ntok_by_slot = state["ntok_by_slot"]
            self._nvalid_host = state["nvalid_host"]
            self._tokens_stored = int(state["tokens_stored"])
            self._raw_tokens_live = int(state["raw_tokens_live"])
            self.generation = int(state["generation"])
            self._fns.clear()  # capacity may differ — re-specialize lazily

    # -- serve-path gather --------------------------------------------------
    def gather_submit(
        self,
        query_tokens,
        query_mask: np.ndarray,
        cand_keys: List[List[int]],
        k_out: int,
        deadline=None,
        width: Optional[int] = None,
    ):
        """Dispatch the fused gather+MaxSim+top-k for one serve batch;
        returns ``(complete, missing)`` where ``complete() -> (scores
        [nq, k_out], perm [nq, k_out])`` (perm indexes each row of
        ``cand_keys``) and ``missing[qi]`` lists candidate POSITIONS not
        resident in the forward index (the caller backfills them from
        the previous stage's ordering).  Raises ``ForwardUnavailable``
        when nothing useful is resident — the rerank stage converts that
        into the ``late_interaction_skipped`` rung.

        The dispatch launches while HOLDING the index lock: the donated
        absorb scatter may replace the row buckets at any commit, so the
        gather must snapshot refs and launch before unlocking — the same
        launch-before-unlock rule as the IVF dispatch (ops/serving.py).
        """
        if query_tokens is None:
            raise ForwardUnavailable("no query token states from stage 1")
        B, Lq = int(query_tokens.shape[0]), int(query_tokens.shape[1])
        nq = len(cand_keys)
        longest = max((len(row) for row in cand_keys), default=0)
        # the candidate grid is pinned to the STAGE's fixed width (not
        # the longest row): a growing corpus widening stage-1 rows must
        # not walk the gather kernel through new compile shapes
        Kc = max(int(width) if width else longest, longest, 1)
        k_out = min(int(k_out), Kc)  # top-k cannot exceed the pool width
        if deadline is not None:
            deadline.check("forward.gather")
        # cheap unlocked emptiness peek BEFORE paying the mask coercion:
        # an empty index raises ForwardUnavailable without a host sync
        # or upload (the authoritative re-check runs under the lock)
        if self._tok is None or not self._slot_of_key:
            raise ForwardUnavailable("forward index is empty")
        # the query mask is caller-provided (possibly an unfetched device
        # array from stage 1): coerce + upload OFF the index lock so the
        # implicit sync never stalls a concurrent absorb commit
        mask_dev = jnp.asarray(np.asarray(query_mask, np.float32))
        with self._lock:
            if self._tok is None or not self._slot_of_key:
                raise ForwardUnavailable("forward index is empty")
            slots = np.full((B, Kc), -1, np.int32)
            missing: List[List[int]] = []
            n_missing = 0
            for qi, row in enumerate(cand_keys):
                miss: List[int] = []
                for j, key in enumerate(row[:Kc]):
                    slot = self._slot_of_key.get(int(key))
                    if slot is None:
                        miss.append(j)
                        n_missing += 1
                    else:
                        slots[qi, j] = slot
                missing.append(miss)
            n_cand = sum(len(row) for row in cand_keys)
            if n_missing >= n_cand:
                raise ForwardUnavailable("no candidate is resident")
            fn = self._maxsim_fn(B, Lq, Kc, k_out)
            # transient gather failures retry briefly (the lock is held,
            # so the budget is milliseconds); "forward.gather" is the
            # chaos-suite fault site
            out = retry_call(  # pathway: allow(lock-discipline, recompile-hazard): dispatch-only — the donated absorb buffers force launch-before-unlock, exactly like the IVF serve dispatch (fetch happens off-lock in the completion); shapes are pinned: B/Lq ride the bucketed stage-1 batch, Kc is the stage's fixed candidate width, capacity doubles
                "forward.gather",
                fn,
                query_tokens,
                mask_dev,
                self._tok,
                self._scales,
                self._nvalid,
                jnp.asarray(slots),
                deadline=deadline,
                policy=_GATHER_RETRY,
            )
            self.stats["gathers"] += 1
            self.stats["gather_candidates"] += n_cand
            self.stats["gather_missing"] += n_missing
        record_dispatch("rerank_maxsim")
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        # gather-batch occupancy: real candidates inside the padded
        # [B, Kc] slot grid (flight recorder)
        observe.record_occupancy("forward_gather", n_cand, B * Kc)

        def complete() -> Tuple[np.ndarray, np.ndarray]:
            inject.fire("forward.gather.fetch", deadline=deadline)
            if deadline is not None:
                deadline.check("forward.gather.fetch")
            arr = np.asarray(out)[:nq]
            record_fetch("rerank_maxsim")
            scores = np.ascontiguousarray(arr[:, :k_out]).view(np.float32)
            perm = arr[:, k_out:]
            return scores, perm

        return complete, missing

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        """Scrape-time ``pathway_forward_*`` samples: residency gauges
        from live state, ingest/gather counters from ``stats``.
        Lock-free reads of GIL-consistent attributes."""
        labels = {"index": str(self._observe_id)}
        n = len(self._slot_of_key)
        yield ("gauge", "pathway_forward_docs", labels, n)
        yield (
            "gauge",
            "pathway_forward_rows_resident",
            labels,
            n * self.tokens_per_doc,
        )
        yield ("gauge", "pathway_forward_tokens_stored", labels, self._tokens_stored)
        yield ("gauge", "pathway_forward_hbm_bytes", labels, self.hbm_bytes())
        yield (
            "gauge",
            "pathway_forward_compression_ratio",
            labels,
            self.compression_ratio(),
        )
        if self._quant_abs_err is not None:
            yield (
                "gauge",
                "pathway_forward_quant_abs_err",
                labels,
                self._quant_abs_err,
            )
        for kind in ("absorbs", "docs_absorbed", "gathers"):
            yield (
                "counter",
                f"pathway_forward_{kind}_total",
                labels,
                self.stats[kind],
            )
        for site, key in (
            ("absorb", "absorb_failures"),
            ("upload", "upload_failures"),
        ):
            yield (
                "counter",
                "pathway_forward_absorb_failures_total",
                {**labels, "site": site},
                self.stats[key],
            )
        for kind, key in (
            ("candidates", "gather_candidates"),
            ("missing", "gather_missing"),
        ):
            yield (
                "counter",
                "pathway_forward_gather_rows_total",
                {**labels, "kind": kind},
                self.stats[key],
            )


class _ShardForward(ForwardIndex):
    """One shard-resident forward partition: commits pin the row buckets
    to the shard's device.  The absorb PLAN (encoder dispatch + pool +
    quantize) stays on the model's device — only the ~10x-compressed
    rows ship to the owning shard at commit time, exactly the traffic
    shape the compression exists for."""

    def __init__(self, *args, device=None, **kwargs):
        self._device = device
        super().__init__(*args, **kwargs)

    def _commit_absorb(self, plan):
        if self._device is None:
            return super()._commit_absorb(plan)
        plan = dict(plan)
        for field in ("q", "scales", "nvalid"):
            plan[field] = jax.device_put(plan[field], self._device)
        with jax.default_device(self._device):
            return super()._commit_absorb(plan)


class ShardedForwardIndex:
    """Document-sharded forward index over the SAME serve device group
    (``parallel.ShardGroup``) as the sharded IVF tier: a document's
    compressed token rows live on the shard that owns its IVF postings,
    so the late-interaction rerank gathers ONLY from each candidate's
    owning shard — no shard ever touches (or stores) rows for documents
    it doesn't own.

    Serve path: per shard, gather+dequantize+MaxSim produce the raw
    ``[B, Kc]`` candidate score table (``-inf`` for candidates the shard
    doesn't own — ownership is disjoint by routing, so every cell has at
    most one finite contributor); the tables hop to the merge device and
    one elementwise-max + top-k kernel emits the same packed output the
    single-index kernel produces.  The merged table is bit-identical to
    an unsharded ``ForwardIndex`` holding every row, one logical
    dispatch + one fetch either way (per-shard-group accounting carries
    the physical fan-out).

    ``gather_submit`` keeps the single-index contract, so
    ``LateInteractionStage`` drops it in unchanged."""

    def __init__(
        self,
        encoder,
        group=None,
        n_shards: Optional[int] = None,
        devices: Optional[Sequence] = None,
        tokens_per_doc: Optional[int] = None,
        quant: Optional[str] = None,
        initial_capacity: int = 1024,
    ):
        from ..parallel.shards import ShardGroup

        self.group = group or ShardGroup(n_shards=n_shards, devices=devices)
        self.encoder = encoder
        self.tokens_per_doc = tokens_per_doc or forward_tokens_per_doc()
        self.quant = quant if quant in ("int8", "none") else forward_quant_mode()
        self.dimension = int(encoder.config.d_model)
        self._lock = threading.Lock()
        self._gen_base = 0
        self.shards: List[_ShardForward] = [
            _ShardForward(
                encoder,
                device=self.group.device(s),
                tokens_per_doc=self.tokens_per_doc,
                quant=self.quant,
                initial_capacity=initial_capacity,
            )
            for s in range(self.group.n_shards)
        ]
        self._fns: Dict[Tuple, Any] = {}
        self._tripwire = RecompileTripwire("ShardedForwardIndex")
        self.stats = {"route_drops": 0, "route_drop_docs": 0}
        self._observe_id = observe.next_id()
        observe.register_provider(self)

    # -- introspection ------------------------------------------------------
    def __len__(self) -> int:
        return sum(len(c) for c in self.shards)

    def __contains__(self, key: int) -> bool:
        key = int(key)
        return key in self.shards[self.group.owner_of(key)]

    @property
    def generation(self) -> int:
        return self._gen_base + sum(c.generation for c in self.shards)

    def hbm_bytes(self) -> int:
        return sum(c.hbm_bytes() for c in self.shards)

    # -- ingest (routed to the owning shard) --------------------------------
    def add(self, keys: Sequence[int], texts: Sequence[str]) -> int:
        keys = [int(k) for k in keys]
        if not keys:
            return 0
        committed = 0
        for s, rows in sorted(self.group.route(keys).items()):
            try:
                inject.fire(f"shard.absorb.{s}")
                inject.fire("shard.absorb")
                committed += self.shards[s].add(
                    [keys[i] for i in rows], [texts[i] for i in rows]
                )
            except Exception as exc:
                with self._lock:
                    self.stats["route_drops"] += 1
                    self.stats["route_drop_docs"] += len(rows)
                    self._gen_base += 1
                log_once(
                    f"shard.absorb.forward:{type(exc).__name__}",
                    "sharded forward ingest to shard %d failed (%r); its "
                    "documents stay out of the forward index only "
                    "(late-interaction degrades, serving continues)",
                    s,
                    exc,
                )
        return committed

    def remove(self, keys: Sequence[int]) -> None:
        keys = [int(k) for k in keys]
        for s, rows in sorted(self.group.route(keys).items()):
            self.shards[s].remove([keys[i] for i in rows])

    # -- compiled fns -------------------------------------------------------
    def _table_fn(self, B: int, Lq: int, Kc: int, capacity: int):
        key = ("table", B, Lq, Kc, capacity, self.tokens_per_doc)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                self._tripwire.observe(key)
                fn = self._fns[key] = profile.wrap(
                    "forward.table",
                    build_maxsim_table_kernel(
                        B, Lq, Kc, self.tokens_per_doc, self.quant == "int8"
                    ),
                )
            return fn

    def _merge_fn(self, S: int, B: int, Kc: int, k_out: int):
        key = ("merge", S, B, Kc, k_out)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                self._tripwire.observe(key)
                fn = self._fns[key] = profile.wrap(
                    "forward.table_merge",
                    build_table_merge_kernel(S, B, Kc, k_out),
                )
            return fn

    # -- serve-path gather --------------------------------------------------
    def gather_submit(
        self,
        query_tokens,
        query_mask: np.ndarray,
        cand_keys: List[List[int]],
        k_out: int,
        deadline=None,
        width: Optional[int] = None,
    ):
        """Sharded flavor of ``ForwardIndex.gather_submit`` — same
        contract, so the late-interaction stage is unchanged.  Fan-out:
        the stage-1 query token states hop to each owning shard, every
        shard scores ONLY the candidates it owns, and the merge device
        max-combines the disjoint tables into one packed top-k.  A
        candidate resident on NO shard is ``missing`` and backfilled by
        the caller from the previous stage's ordering."""
        if query_tokens is None:
            raise ForwardUnavailable("no query token states from stage 1")
        B, Lq = int(query_tokens.shape[0]), int(query_tokens.shape[1])
        nq = len(cand_keys)
        longest = max((len(row) for row in cand_keys), default=0)
        Kc = max(int(width) if width else longest, longest, 1)
        k_out = min(int(k_out), Kc)
        if deadline is not None:
            deadline.check("forward.gather")
        qmask_np = np.asarray(query_mask, np.float32)
        tables: List[Any] = []
        physical = 0
        owned = np.zeros((B, Kc), bool)
        n_cand = sum(len(row) for row in cand_keys)
        for s, child in enumerate(self.shards):
            dev = self.group.device(s)
            with child._lock:
                if child._tok is None or not child._slot_of_key:
                    continue
                slots = np.full((B, Kc), -1, np.int32)
                any_owned = False
                for qi, row in enumerate(cand_keys):
                    for j, key in enumerate(row[:Kc]):
                        slot = child._slot_of_key.get(int(key))
                        if slot is not None:
                            slots[qi, j] = slot
                            owned[qi, j] = True
                            any_owned = True
                if not any_owned:
                    continue
                fn = self._table_fn(B, Lq, Kc, child._capacity)
                with jax.default_device(dev):
                    qtok_s = jax.device_put(query_tokens, dev)  # pathway: allow(lock-discipline): device→device scatter of the UNFETCHED stage-1 query token states — an async ICI hop, not a host transfer; it must precede the gather launch that consumes it under this lock
                    out = retry_call(  # pathway: allow(lock-discipline, recompile-hazard): dispatch-only — the shard's donated absorb buffers force launch-before-unlock (fetch happens after the merge, off-lock); shapes pinned like the single-index gather
                        "forward.gather",
                        fn,
                        qtok_s,
                        jnp.asarray(qmask_np),
                        child._tok,
                        child._scales,
                        child._nvalid,
                        jnp.asarray(slots),
                        deadline=deadline,
                        policy=_GATHER_RETRY,
                    )
                child.stats["gathers"] += 1
            tables.append(out)
            physical += 1
        missing: List[List[int]] = []
        n_missing = 0
        for qi, row in enumerate(cand_keys):
            miss = [j for j in range(len(row[:Kc])) if not owned[qi, j]]
            n_missing += len(miss)
            missing.append(miss)
        if not tables or n_missing >= n_cand:
            raise ForwardUnavailable("no candidate is resident on any shard")
        merge_dev = getattr(query_tokens, "device", None) or self.group.device(0)
        moved = [jax.device_put(t, merge_dev) for t in tables]
        mfn = self._merge_fn(len(moved), B, Kc, k_out)
        out = retry_call(
            "shard.merge", mfn, *moved, deadline=deadline, policy=_GATHER_RETRY
        )
        record_dispatch("rerank_maxsim", shards=physical + 1)
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        observe.record_occupancy("forward_gather", n_cand, B * Kc)

        def complete() -> Tuple[np.ndarray, np.ndarray]:
            inject.fire("forward.gather.fetch", deadline=deadline)
            if deadline is not None:
                deadline.check("forward.gather.fetch")
            arr = np.asarray(out)[:nq]
            record_fetch("rerank_maxsim")
            scores = np.ascontiguousarray(arr[:, :k_out]).view(np.float32)
            perm = arr[:, k_out:]
            return scores, perm

        return complete, missing

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        labels = {"index": str(self._observe_id)}
        yield (
            "counter",
            "pathway_serve_shard_ingest_drops_total",
            {**labels, "tier": "forward"},
            self.stats["route_drops"],
        )
        for s, child in enumerate(self.shards):
            yield (
                "gauge",
                "pathway_serve_shard_forward_docs",
                {**labels, "shard": str(s)},
                len(child),
            )
