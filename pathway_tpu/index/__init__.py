"""Device-resident forward index for late-interaction reranking.

``ForwardIndex`` (``forward.py``) stores compressed per-document token
representations in HBM at ingest time — fixed-budget token pooling to a
small row count plus per-channel int8 quantization with stored scales —
so the serve-time rerank stage is a single fused gather + dequantize +
MaxSim + top-k dispatch (ops/maxsim.py) instead of a cross-encoder
forward over every candidate pair.  The ingest path mirrors
``ops/ivf.py``'s absorb/commit discipline: plan off-lock, commit locked,
generation/staleness guards.
"""

from .forward import (
    ForwardIndex,
    ForwardUnavailable,
    ShardedForwardIndex,
    forward_quant_mode,
    forward_tokens_per_doc,
)

__all__ = [
    "ForwardIndex",
    "ForwardUnavailable",
    "ShardedForwardIndex",
    "forward_quant_mode",
    "forward_tokens_per_doc",
]
