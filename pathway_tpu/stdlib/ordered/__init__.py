"""pw.ordered — diff over a sort key
(reference: python/pathway/stdlib/ordered/diff.py:10)."""

from __future__ import annotations

from ...internals import api_reducers as reducers
from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["diff"]


def diff(table: Table, timestamp, *values, instance=None) -> Table:
    """Difference of each value column vs. the previous row in timestamp order."""
    names = [v.name for v in values]
    packed = table.groupby(*([] if instance is None else [instance])).reduce(
        _pw_rows=reducers.sorted_tuple(
            ApplyExpression(
                lambda t, *vals: (t, vals),
                dt.ANY,
                args=(timestamp, *values),
            )
        )
    )

    def diffs(rows):
        out = []
        prev = None
        for t, vals in rows:
            if prev is None:
                out.append((t, tuple(None for _ in vals)))
            else:
                out.append((t, tuple(v - p for v, p in zip(vals, prev))))
            prev = vals
        return out

    exploded = packed.select(
        _pw_diffs=ApplyExpression(diffs, dt.ANY, args=(packed._pw_rows,))
    ).flatten(this._pw_diffs)
    result = exploded.select(
        timestamp=ApplyExpression(lambda d: d[0], dt.ANY, args=(this._pw_diffs,)),
        **{
            f"diff_{name}": ApplyExpression(
                lambda d, _i=i: d[1][_i], dt.ANY, args=(this._pw_diffs,)
            )
            for i, name in enumerate(names)
        },
    )
    return result
