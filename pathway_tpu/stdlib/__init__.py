"""pw.stdlib (reference: python/pathway/stdlib/ — SURVEY.md §2.9)."""

from . import graphs, indexing, ml, ordered, stateful, statistical, temporal, utils, viz

__all__ = [
    "graphs",
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
    "viz",
]
