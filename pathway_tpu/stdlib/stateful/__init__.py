"""pw.stateful (reference: python/pathway/stdlib/stateful/deduplicate.py:9)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from ...internals.table import Table

__all__ = ["deduplicate"]


def deduplicate(
    table: Table,
    *,
    value,
    instance=None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: Optional[str] = None,
    name: str = "deduplicate",
) -> Table:
    return table.deduplicate(
        value=value, instance=instance, acceptor=acceptor, name=name
    )
