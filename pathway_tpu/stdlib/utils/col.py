"""Column utilities (reference: python/pathway/stdlib/utils/col.py:367)."""

from __future__ import annotations

from typing import Callable, List, Optional

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.table import Table

__all__ = ["unpack_col", "multiapply_all_rows", "apply_all_rows", "flatten_column"]


def unpack_col(column: ColumnReference, *unpacked_columns, schema=None) -> Table:
    """Unpack a tuple column into named columns."""
    table = column.table
    if schema is not None:
        names = list(schema.columns().keys())
    else:
        names = [
            c.name if isinstance(c, ColumnReference) else str(c)
            for c in unpacked_columns
        ]
    return table.select(
        **{
            name: ApplyExpression(
                lambda v, _i=i: v[_i] if v is not None else None,
                dt.ANY,
                args=(column,),
            )
            for i, name in enumerate(names)
        }
    )


def apply_all_rows(
    *cols: ColumnReference,
    fun: Callable,
    result_col_name: str,
) -> Table:
    """Apply ``fun`` to entire columns at once (lists of all rows) — the
    batched escape hatch (reference: col.py apply_all_rows)."""
    table = cols[0].table
    return table.select(
        **{
            result_col_name: ApplyExpression(
                lambda *arrays: fun(*[list(a) for a in arrays]),
                dt.ANY,
                args=cols,
                batched=True,
            )
        }
    )


multiapply_all_rows = apply_all_rows


def flatten_column(column: ColumnReference, origin_id: Optional[str] = None) -> Table:
    table = column.table
    return table.flatten(column)
