"""pw.utils (reference: python/pathway/stdlib/utils/)."""

from . import col
from .col import apply_all_rows, flatten_column, multiapply_all_rows, unpack_col

try:  # AsyncTransformer depends only on stdlib pieces but import defensively
    from .async_transformer import AsyncTransformer
except ImportError:  # pragma: no cover
    AsyncTransformer = None

__all__ = [
    "col",
    "unpack_col",
    "apply_all_rows",
    "multiapply_all_rows",
    "flatten_column",
    "AsyncTransformer",
]
