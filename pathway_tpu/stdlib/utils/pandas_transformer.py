"""``pw.pandas_transformer`` — wrap a pandas function as a Table transform.

Reference: python/pathway/stdlib/utils/pandas_transformer.py:124.  Semantics
kept: each input Table is materialized as a pandas DataFrame (indexed by row
id) on every update, the user function runs on whole frames, and its output
DataFrame becomes a Table typed by ``output_schema``; ``output_universe``
(argument name or index) asserts the result keeps that input's index.  Like
the reference, this is deliberately *non-incremental* — each tick recomputes
from the full frames (the packed global reduce makes that explicit).
"""

from __future__ import annotations

import inspect
from typing import Optional, Union

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.keys import Pointer, ref_scalar
from ...internals.table import Table
from .col import unpack_col

__all__ = ["pandas_transformer"]


def _packed_frame(table: Table):
    """One-row Table with the whole input packed as a tuple-of-row-tuples.

    A ``tuple`` reducer (not a batched select) so the pack tracks the full
    accumulated table state across deltas, with retractions handled."""
    from ...internals import api_reducers

    names = table.column_names
    cols = [table[name] for name in names]
    tupled = table.select(
        _row=ApplyExpression(
            lambda *a: (Pointer(int(a[0])),) + tuple(a[1:]),
            dt.ANY,
            args=(table.id, *cols),
        )
    )
    return tupled.reduce(_all=api_reducers.tuple(tupled._row))


def _as_dataframe(rows, column_names):
    import pandas as pd

    rows = rows or ()
    # object dtype: a plain list of Pointers would coerce to Int64Index,
    # losing the "this is an engine key" marker
    index = pd.Index([r[0] for r in rows], dtype=object)
    data = {
        name: [r[i + 1] for r in rows] for i, name in enumerate(column_names)
    }
    df = pd.DataFrame(data, index=index)
    return df


def _argument_index(func, arg: Union[str, int, None]) -> Optional[int]:
    if arg is None:
        return None
    names = list(inspect.signature(func).parameters)
    if isinstance(arg, str):
        if arg not in names:
            raise ValueError(f"wrong output universe. No argument of name: {arg}")
        return names.index(arg)
    if arg < 0 or arg >= len(names):
        raise ValueError("wrong output universe. Index out of range")
    return arg


def pandas_transformer(
    output_schema, output_universe: Union[str, int, None] = None
):
    """Decorator: ``func(*frames: pd.DataFrame) -> pd.DataFrame`` becomes
    ``func(*tables: pw.Table) -> pw.Table``."""

    def decorator(func):
        universe_index = _argument_index(func, output_universe)

        def transformer(*inputs: Table) -> Table:
            import pandas as pd

            if not inputs:
                from ... import debug

                result = func()
                if isinstance(result, pd.Series):
                    result = pd.DataFrame(result)
                result.columns = output_schema.column_names()
                return debug.table_from_pandas(result).update_types(
                    **output_schema.typehints()
                )

            # one-row table holding every input's packed tuple (cross join of
            # the per-input global reduces)
            packed = [_packed_frame(t) for t in inputs]
            combined = packed[0].select(_0=packed[0]._all)
            for idx in range(1, len(packed)):
                combined = combined.join(packed[idx]).select(
                    **{f"_{i}": combined[f"_{i}"] for i in range(idx)},
                    **{f"_{idx}": packed[idx]._all},
                )

            input_names = [t.column_names for t in inputs]

            def run(*packed_rows):
                frames = [
                    _as_dataframe(rows, names)
                    for rows, names in zip(packed_rows, input_names)
                ]
                result = func(*frames)
                if isinstance(result, pd.Series):
                    result = pd.DataFrame(result)
                result.columns = output_schema.column_names()
                if universe_index is not None:
                    if not result.index.equals(frames[universe_index].index):
                        raise ValueError(
                            "resulting universe does not match the universe"
                            " of the indicated argument"
                        )
                else:
                    if not result.index.is_unique:
                        raise ValueError(
                            "index of resulting DataFrame must be unique"
                        )
                out = []
                for rid, row in zip(result.index, result.itertuples(index=False)):
                    # Pointer index values are engine keys carried over from an
                    # input frame (table.id); anything else is user data to hash
                    if not isinstance(rid, Pointer):
                        rid = ref_scalar(rid)
                    out.append((rid,) + tuple(row))
                return tuple(out)

            applied = combined.select(
                _rows=ApplyExpression(
                    run,
                    dt.ANY,
                    args=tuple(combined[f"_{i}"] for i in range(len(packed))),
                )
            )
            flat = applied.flatten(applied._rows)
            unpacked = unpack_col(
                flat._rows, "_id", *output_schema.column_names()
            )
            out = unpacked.with_id(unpacked._id).without("_id")
            return out.update_types(**output_schema.typehints())

        return transformer

    return decorator
