"""AsyncTransformer — fully-async table transformation
(reference: python/pathway/stdlib/utils/async_transformer.py:282).

Rows are handed to an async ``invoke``; results re-enter the dataflow as a
*new source* at later timestamps (the reference's loop-back through a python
connector), so slow external calls never block the engine tick."""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional, Type

from ...internals import dtype as dt
from ...internals.parse_graph import G
from ...internals.schema import Schema
from ...internals.table import Table
from ...io._connector import SessionWriter, register_source
from ...io._subscribe import subscribe

__all__ = ["AsyncTransformer"]


class AsyncTransformer:
    """Subclass, define ``output_schema`` and ``async def invoke(self, **row)``.

    ``transformer(input_table).successful`` is the table of results."""

    output_schema: Type[Schema]

    def __init__(self, input_table: Optional[Table] = None, **kwargs):
        self._input_table = input_table
        self._instance_kwargs = kwargs
        self._result_table: Optional[Table] = None
        if input_table is not None:
            self._build()

    def __call__(self, input_table: Table) -> "AsyncTransformer":
        self._input_table = input_table
        self._build()
        return self

    async def invoke(self, **kwargs) -> Dict[str, Any]:
        raise NotImplementedError

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def _build(self) -> None:
        input_table = self._input_table
        schema = self.output_schema
        names = input_table.column_names
        pending: "asyncio.Queue" = None  # created inside the worker loop
        writer_holder: Dict[str, SessionWriter] = {}
        stop = threading.Event()
        queue_items = []
        queue_lock = threading.Lock()
        queue_event = threading.Event()

        transformer = self

        # shared in-flight counter: incremented UNDER the queue lock when
        # items leave the queue, decremented only after the result row is in
        # the session — the executor's quiescence probe must never observe
        # "empty queue, zero in flight" while an invocation is pending
        inflight_n = [0]

        def runner(writer: SessionWriter):
            writer_holder["w"] = writer
            transformer.open()

            async def work():
                in_flight = set()
                while not stop.is_set() or queue_items or in_flight:
                    with queue_lock:
                        items, queue_items[:] = queue_items[:], []
                        inflight_n[0] += len(items)
                    for key, row in items:
                        async def one(key=key, row=row):
                            try:
                                result = await transformer.invoke(**row)
                                if isinstance(result, dict):
                                    writer.insert(result, key=key)
                            except Exception:
                                import logging

                                logging.getLogger(__name__).exception(
                                    "AsyncTransformer.invoke failed"
                                )
                            finally:
                                with queue_lock:
                                    inflight_n[0] -= 1

                        in_flight.add(asyncio.ensure_future(one()))
                    if in_flight:
                        done, in_flight = await asyncio.wait(
                            in_flight, timeout=0.05, return_when=asyncio.FIRST_COMPLETED
                        )
                    else:
                        await asyncio.sleep(0.02)

            asyncio.run(work())
            transformer.close()

        def quiesced() -> bool:
            with queue_lock:
                return not queue_items and inflight_n[0] == 0

        # distributed: the input subscriber GATHERS to rank 0, so invoke()
        # runs once per row cluster-wide; the loop-back source is therefore
        # disjoint-by-construction (only rank 0 produces) and registers as
        # "partitioned" so results re-scatter to their key owners — the
        # default replicated-filter would silently drop rows owned by other
        # ranks
        result = register_source(
            schema,
            runner,
            mode="streaming",
            name="async_transformer",
            dist_mode="partitioned",
            quiesce_check=quiesced,
        )

        def on_change(key, row, time, is_addition):
            if not is_addition:
                return
            with queue_lock:
                queue_items.append((int(key), dict(row)))

        def on_end():
            stop.set()

        subscribe(self._input_table, on_change=on_change, on_end=on_end)
        self._result_table = result

    @property
    def successful(self) -> Table:
        assert self._result_table is not None
        return self._result_table

    @property
    def output_table(self) -> Table:
        return self.successful

    def with_options(self, **kwargs) -> "AsyncTransformer":
        return self
