"""Hybrid retrieval: fuse rankings from several indexes with Reciprocal Rank
Fusion (reference: stdlib/indexing/hybrid_index.py:14 HybridIndex)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .nearest_neighbors import InnerIndexImpl

__all__ = ["HybridIndex", "HybridIndexFactory"]


class HybridIndexImpl(InnerIndexImpl):
    def __init__(self, inner_indexes: Sequence[InnerIndexImpl], k_constant: float = 60.0):
        self.indexes = list(inner_indexes)
        self.k_constant = k_constant

    def add(self, keys, values, metadatas) -> None:
        # values is a tuple-per-row: one value per sub-index (e.g. (vector, text))
        for i, index in enumerate(self.indexes):
            index.add(keys, [v[i] for v in values], metadatas)

    def remove(self, keys) -> None:
        for index in self.indexes:
            index.remove(keys)

    def search(self, values, k, filters):
        per_index = [
            index.search([v[i] for v in values], k * 2, filters)
            for i, index in enumerate(self.indexes)
        ]
        out = []
        for qi in range(len(values)):
            fused: Dict[int, float] = {}
            for index_results in per_index:
                for rank, (key, _score) in enumerate(index_results[qi]):
                    fused[key] = fused.get(key, 0.0) + 1.0 / (
                        self.k_constant + rank + 1
                    )
            ranked = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
            out.append(tuple(ranked))
        return out


class HybridIndexFactory:
    """(reference: HybridIndexFactory, hybrid_index.py)"""

    def __init__(self, retriever_factories: Sequence, k: float = 60.0, **kwargs):
        self.retriever_factories = list(retriever_factories)
        self.k = k

    def build_inner_index(self, dimension: Optional[int] = None) -> HybridIndexImpl:
        return HybridIndexImpl(
            [f.build_inner_index(dimension) for f in self.retriever_factories],
            k_constant=self.k,
        )


HybridIndex = HybridIndexFactory
