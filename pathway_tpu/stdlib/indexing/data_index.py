"""DataIndex — query API over live retrieval indexes
(reference: stdlib/indexing/data_index.py:278 DataIndex, :206 InnerIndex;
``query()`` = fully consistent/retracting, ``query_as_of_now()`` =
non-retracting serving contract, data_index.py:364-441).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...engine.operators.external_index import ExternalIndexOperator
from ...internals import dtype as dt
from ...internals.expression import (
    ApplyExpression,
    ColumnExpression,
    ColumnReference,
    smart_coerce,
)
from ...internals.parse_graph import G
from ...internals.table import Table
from ...internals.thisclass import this
from ...internals.universe import Universe

__all__ = ["InnerIndex", "DataIndex", "IndexQueryResult"]


class InnerIndex:
    """Descriptor of an index over one column of a data table."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: Optional[ColumnReference] = None,
        factory=None,
        dimension: Optional[int] = None,
    ):
        self.data_column = data_column
        self.metadata_column = metadata_column
        self.factory = factory
        self.dimension = dimension

    @property
    def data_table(self) -> Table:
        return self.data_column.table


class DataIndex:
    """(reference DataIndex, data_index.py:278)"""

    def __init__(
        self,
        data_table: Table,
        inner_index: InnerIndex,
    ):
        self.data_table = data_table
        self.inner_index = inner_index

    def _build(self, query_column, k, metadata_filter, asof_now: bool) -> Table:
        from ...internals.expression import ColumnExpression as _CE

        k_expr = None
        if isinstance(k, _CE):
            k_expr, k = k, 16
        query_expr = smart_coerce(query_column)
        refs = [r for r in query_expr._column_refs() if isinstance(r.table, Table)]
        if not refs:
            raise ValueError("query column must reference a query table")
        query_table = refs[0].table
        data_table = self.data_table
        index_impl = self.inner_index.factory.build_inner_index(
            self.inner_index.dimension
        )
        reply_et = G.engine_graph.add_table(["_pw_qkey", "_pw_reply"], "index_reply")
        filter_expr = smart_coerce(metadata_filter) if metadata_filter is not None else None
        op = ExternalIndexOperator(
            data_table._engine_table,
            query_table._engine_table,
            reply_et,
            index=index_impl,
            data_expr=smart_coerce(self.inner_index.data_column),
            data_ctx=data_table._ctx_cols(placeholders=[this]),
            query_expr=query_expr,
            query_ctx=query_table._ctx_cols(placeholders=[this]),
            k=k,
            k_expr=k_expr,
            metadata_expr=smart_coerce(self.inner_index.metadata_column)
            if self.inner_index.metadata_column is not None
            else None,
            filter_expr=filter_expr,
            asof_now=asof_now,
            name="external_index" + ("_asof_now" if asof_now else ""),
        )
        G.engine_graph.add_operator(op)
        reply_table = Table(
            reply_et,
            {"_pw_qkey": dt.POINTER, "_pw_reply": dt.ANY},
            query_table._universe,
            short_name="index_reply",
        )
        return query_table, reply_table

    def query_as_of_now(
        self,
        query_column,
        *,
        number_of_matches: int = 3,
        collapse_rows: bool = True,
        metadata_filter=None,
        **kwargs,
    ) -> "IndexQueryResult":
        query_table, reply = self._build(
            query_column, number_of_matches, metadata_filter, asof_now=True
        )
        return IndexQueryResult(self, query_table, reply, collapse_rows)

    def query(
        self,
        query_column,
        *,
        number_of_matches: int = 3,
        collapse_rows: bool = True,
        metadata_filter=None,
        **kwargs,
    ) -> "IndexQueryResult":
        query_table, reply = self._build(
            query_column, number_of_matches, metadata_filter, asof_now=False
        )
        return IndexQueryResult(self, query_table, reply, collapse_rows)


class _ScoreMarker:
    """Placeholder expression for the match score inside result.select()."""


SCORE = _ScoreMarker()


class IndexQueryResult:
    """Supports ``.select(...)`` with columns from the query table (scalar per
    query) and the data table (tuple per query when collapsed, scalar per
    match otherwise); ``result.score`` gives similarity scores."""

    def __init__(
        self,
        index: DataIndex,
        query_table: Table,
        reply_table: Table,
        collapse_rows: bool,
    ):
        self._index = index
        self._query_table = query_table
        self._reply = reply_table
        self._collapse = collapse_rows

    @property
    def score(self) -> _ScoreMarker:
        return SCORE

    # -- data lookup helpers ----------------------------------------------
    def _data_lookup_fn(self, api_col: str) -> Callable[[int], Any]:
        data = self._index.data_table
        engine_col = data._column_mapping[api_col]
        store = data._engine_table.store
        idx = store.column_names.index(engine_col)

        def lookup(key: int):
            row = store.get(int(key))
            return row[idx] if row is not None else None

        return lookup

    def _remap_collapsed(self, expr):
        """Data-table refs -> tuple-valued applies over the reply column."""
        if isinstance(expr, _ScoreMarker):
            return ApplyExpression(
                lambda reply: tuple(float(s) for _k, s in reply),
                dt.ANY,
                args=(self._reply._pw_reply,),
            )
        if isinstance(expr, ColumnReference) and expr.table is self._index.data_table:
            lookup = self._data_lookup_fn(expr.name)
            return ApplyExpression(
                lambda reply, _f=lookup: tuple(_f(k) for k, _s in reply),
                dt.ANY,
                args=(self._reply._pw_reply,),
            )
        if isinstance(expr, ColumnExpression):
            import copy

            new = copy.copy(expr)
            for attr, value in list(vars(new).items()):
                if isinstance(value, (ColumnExpression, _ScoreMarker)):
                    setattr(new, attr, self._remap_collapsed(value))
                elif isinstance(value, tuple) and any(
                    isinstance(v, (ColumnExpression, _ScoreMarker)) for v in value
                ):
                    setattr(
                        new,
                        attr,
                        tuple(
                            self._remap_collapsed(v)
                            if isinstance(v, (ColumnExpression, _ScoreMarker))
                            else v
                            for v in value
                        ),
                    )
            new._deps = tuple(
                self._remap_collapsed(d) if isinstance(d, (ColumnExpression, _ScoreMarker)) else d
                for d in getattr(new, "_deps", ())
            )
            return new
        return expr

    def select(self, *args, **kwargs) -> Table:
        exprs: Dict[str, Any] = {}
        for arg in args:
            if isinstance(arg, ColumnReference):
                exprs[arg.name] = arg
            else:
                raise ValueError("positional select args must be column references")
        exprs.update(kwargs)
        if self._collapse:
            out = {name: self._remap_collapsed(e) for name, e in exprs.items()}
            return self._query_table.select(**out)
        # non-collapsed: one row per (query, match)
        flat = self._reply.flatten(self._reply._pw_reply)
        enriched = flat.select(
            _pw_qkey=flat._pw_qkey,
            _pw_match_key=ApplyExpression(
                lambda m: int(m[0]), dt.POINTER, args=(this._pw_reply,)
            ),
            _pw_score=ApplyExpression(
                lambda m: float(m[1]), dt.FLOAT, args=(this._pw_reply,)
            ),
        )
        out_exprs: Dict[str, ColumnExpression] = {}
        for name, e in exprs.items():
            out_exprs[name] = self._remap_flat(e, enriched)
        return enriched.select(**out_exprs)

    def _remap_flat(self, expr, enriched: Table):
        if isinstance(expr, _ScoreMarker):
            return enriched._pw_score
        if isinstance(expr, ColumnReference) and expr.table is self._index.data_table:
            lookup = self._data_lookup_fn(expr.name)
            return ApplyExpression(
                lambda k, _f=lookup: _f(k), dt.ANY, args=(enriched._pw_match_key,)
            )
        if isinstance(expr, ColumnReference) and (
            expr.table is self._query_table or expr.table is this
        ):
            lookup = self._query_lookup_fn(expr.name)
            return ApplyExpression(
                lambda qk, _f=lookup: _f(qk), dt.ANY, args=(enriched._pw_qkey,)
            )
        if isinstance(expr, ColumnExpression):
            import copy

            new = copy.copy(expr)
            for attr, value in list(vars(new).items()):
                if isinstance(value, (ColumnExpression, _ScoreMarker)):
                    setattr(new, attr, self._remap_flat(value, enriched))
                elif isinstance(value, tuple) and any(
                    isinstance(v, (ColumnExpression, _ScoreMarker)) for v in value
                ):
                    setattr(
                        new,
                        attr,
                        tuple(
                            self._remap_flat(v, enriched)
                            if isinstance(v, (ColumnExpression, _ScoreMarker))
                            else v
                            for v in value
                        ),
                    )
            new._deps = tuple(
                self._remap_flat(d, enriched)
                if isinstance(d, (ColumnExpression, _ScoreMarker))
                else d
                for d in getattr(new, "_deps", ())
            )
            return new
        return expr

    def _query_lookup_fn(self, api_col: str) -> Callable[[int], Any]:
        q = self._query_table
        engine_col = q._column_mapping[api_col]
        store = q._engine_table.store
        idx = store.column_names.index(engine_col)

        def lookup(key: int):
            row = store.get(int(key))
            return row[idx] if row is not None else None

        return lookup
