"""Sorted-structure helpers (reference: stdlib/indexing/sorting.py:230 —
binsearch trees over tables).  Host-side sorted lookup utilities used by the
asof machinery; full tree API lands with pw.iterate."""

from __future__ import annotations

import bisect
from typing import Any, List, Tuple

__all__ = ["binsearch_lower", "binsearch_upper"]


def binsearch_lower(sorted_pairs: List[Tuple[Any, Any]], key: Any):
    """Largest entry with k <= key (None if none)."""
    keys = [k for k, _ in sorted_pairs]
    i = bisect.bisect_right(keys, key) - 1
    return sorted_pairs[i][1] if i >= 0 else None


def binsearch_upper(sorted_pairs: List[Tuple[Any, Any]], key: Any):
    """Smallest entry with k >= key (None if none)."""
    keys = [k for k, _ in sorted_pairs]
    i = bisect.bisect_left(keys, key)
    return sorted_pairs[i][1] if i < len(sorted_pairs) else None
