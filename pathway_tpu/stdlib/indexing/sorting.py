"""Sorted-structure API (reference: stdlib/indexing/sorting.py:230 —
binsearch trees + prev/next retrieval over sorted tables).

The reference builds a randomized binsearch tree with ``pw.iterate`` and
derives prev/next pointers from tree traversal (sort_from_index); here
``Table.sort`` computes prev/next directly in the engine
(engine/operators/sort.py), and this module supplies the value-walking API
on top plus host-side binsearch helpers used by the asof machinery."""

from __future__ import annotations

import bisect
from typing import Any, List, Tuple

__all__ = [
    "binsearch_lower",
    "binsearch_upper",
    "sort_from_index",
    "retrieve_prev_next_values",
]


def binsearch_lower(sorted_pairs: List[Tuple[Any, Any]], key: Any):
    """Largest entry with k <= key (None if none)."""
    keys = [k for k, _ in sorted_pairs]
    i = bisect.bisect_right(keys, key) - 1
    return sorted_pairs[i][1] if i >= 0 else None


def binsearch_upper(sorted_pairs: List[Tuple[Any, Any]], key: Any):
    """Smallest entry with k >= key (None if none)."""
    keys = [k for k, _ in sorted_pairs]
    i = bisect.bisect_left(keys, key)
    return sorted_pairs[i][1] if i < len(sorted_pairs) else None


def sort_from_index(table, key, instance=None):
    """prev/next pointer columns for ``table`` in ``key`` order — the
    reference's tree-derived API (sorting.py:137), served by the engine sort
    operator here."""
    return table.sort(key, instance=instance)


def retrieve_prev_next_values(ordered_table, value=None):
    """For each row of a prev/next-ordered table, pointers-walk to the
    nearest row (itself included) with a non-None ``value`` in each
    direction; returns columns ``prev_value`` / ``next_value``
    (reference: sorting.py:195 — same iterate-to-fixpoint shape)."""
    import pathway_tpu as pw

    if value is None:
        value = ordered_table.value
    elif isinstance(value, str):
        value = getattr(ordered_table, value)

    seeded = ordered_table.select(
        prev=ordered_table.prev,
        next=ordered_table.next,
        value=value,
    )
    seeded = seeded.with_columns(
        prev_value=pw.require(pw.this.id, pw.this.value),
        next_value=pw.require(pw.this.id, pw.this.value),
    )

    def walk(tab):
        return tab.with_columns(
            prev_value=pw.coalesce(
                tab.prev_value,
                tab.ix(tab.prev, optional=True).prev_value,
            ),
            next_value=pw.coalesce(
                tab.next_value,
                tab.ix(tab.next, optional=True).next_value,
            ),
        )

    result = pw.iterate(walk, tab=seeded)
    return result.select(result.prev_value, result.next_value)
