"""Metadata filter language for index queries.

Reference uses JMESPath extended with ``globmatch`` for candidate filtering
(src/external_integration/mod.rs:248 JMESPathFilterWithGlobPattern).  No
jmespath dependency exists here, so this is a self-contained parser for the
subset the reference's docs exercise: dotted field paths, literals,
``== != < <= > >=``, ``&& || !``, parentheses, and the functions
``contains(haystack, needle)`` and ``globmatch(pattern, field)``.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["compile_filter"]

_TOKEN = re.compile(
    r"\s*(?:"
    r"(?P<num>-?\d+\.\d+|-?\d+)"
    r"|(?P<str>'(?:[^']|\\')*'|`(?:[^`])*`|\"(?:[^\"])*\")"
    r"|(?P<name>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op>&&|\|\||==|!=|<=|>=|<|>|!|\(|\)|,|\.)"
    r")"
)


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"bad filter syntax near {text[pos:pos+20]!r}")
        pos = m.end()
        if m.lastgroup == "num":
            out.append(("num", m.group("num")))
        elif m.lastgroup == "str":
            s = m.group("str")
            out.append(("str", s[1:-1]))
        elif m.lastgroup == "name":
            out.append(("name", m.group("name")))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", ""))
    return out


class _P:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind, val=None):
        k, v = self.peek()
        if k == kind and (val is None or v == val):
            self.i += 1
            return v
        return None

    def expect(self, kind, val=None):
        got = self.accept(kind, val)
        if got is None:
            raise ValueError(f"filter: expected {val or kind}, got {self.peek()}")
        return got

    def parse(self):
        e = self.parse_or()
        self.expect("eof")
        return e

    def parse_or(self):
        left = self.parse_and()
        while self.accept("op", "||"):
            right = self.parse_and()
            left = (lambda l, r: lambda m: l(m) or r(m))(left, right)
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept("op", "&&"):
            right = self.parse_not()
            left = (lambda l, r: lambda m: l(m) and r(m))(left, right)
        return left

    def parse_not(self):
        if self.accept("op", "!"):
            inner = self.parse_not()
            return lambda m: not inner(m)
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_atom()
        k, v = self.peek()
        if k == "op" and v in ("==", "!=", "<", "<=", ">", ">="):
            self.next()
            right = self.parse_atom()
            ops = {
                "==": lambda a, b: a == b,
                "!=": lambda a, b: a != b,
                "<": lambda a, b: a is not None and b is not None and a < b,
                "<=": lambda a, b: a is not None and b is not None and a <= b,
                ">": lambda a, b: a is not None and b is not None and a > b,
                ">=": lambda a, b: a is not None and b is not None and a >= b,
            }
            op = ops[v]
            return lambda m: op(left(m), right(m))
        # no comparison: return the raw value (truthiness applies only at
        # boolean-context boundaries, not inside function arguments)
        return left

    def parse_atom(self):
        k, v = self.peek()
        if k == "num":
            self.next()
            value = float(v) if "." in v else int(v)
            return lambda m: value
        if k == "str":
            self.next()
            return lambda m: v
        if self.accept("op", "("):
            inner = self.parse_or()
            self.expect("op", ")")
            return inner
        if k == "name":
            name = self.next()[1]
            if name in ("true", "false", "null"):
                value = {"true": True, "false": False, "null": None}[name]
                return lambda m: value
            if self.peek() == ("op", "("):
                self.next()
                args = []
                if not self.accept("op", ")"):
                    while True:
                        args.append(self.parse_or())
                        if not self.accept("op", ","):
                            break
                    self.expect("op", ")")
                return self._function(name, args)
            # dotted path
            path = [name]
            while self.accept("op", "."):
                path.append(self.expect("name"))

            def lookup(m, _path=tuple(path)):
                cur = m
                for part in _path:
                    if isinstance(cur, dict):
                        cur = cur.get(part)
                    else:
                        return None
                return cur

            return lookup
        raise ValueError(f"filter: unexpected token {self.peek()}")

    def _function(self, name, args):
        if name == "contains":
            a, b = args
            return lambda m: (lambda h, n: h is not None and n in h)(a(m), b(m))
        if name in ("globmatch", "glob_pattern_match"):
            pat, field = args
            return lambda m: (
                lambda p, f: f is not None and fnmatch.fnmatch(str(f), str(p))
            )(pat(m), field(m))
        if name == "starts_with":
            a, b = args
            return lambda m: (lambda s, p: s is not None and str(s).startswith(str(p)))(
                a(m), b(m)
            )
        if name == "length":
            (a,) = args
            return lambda m: (lambda x: len(x) if x is not None else 0)(a(m))
        raise ValueError(f"filter: unknown function {name}")


def compile_filter(expr: Optional[str]) -> Optional[Callable[[Any], bool]]:
    """Compile a filter expression to metadata_dict -> bool (None passes all)."""
    if expr is None or expr == "":
        return None
    fn = _P(_tokenize(expr)).parse()
    return lambda m: bool(fn(m))
