"""pw.indexing — DataIndex over device-resident retrieval indexes
(reference: python/pathway/stdlib/indexing/ — data_index.py:278,
nearest_neighbors.py, bm25.py, hybrid_index.py).

Populated by the index milestone: see data_index.py / nearest_neighbors.py /
bm25.py / hybrid_index.py in this package."""

from __future__ import annotations

try:
    from .data_index import DataIndex, InnerIndex
    from .nearest_neighbors import (
        BruteForceKnn,
        BruteForceKnnFactory,
        IvfKnn,
        IvfKnnFactory,
        LshKnn,
        LshKnnFactory,
        TpuKnn,
        TpuKnnFactory,
        USearchKnn,
        UsearchKnnFactory,
    )
    from .bm25 import TantivyBM25, TantivyBM25Factory, BM25Index
    from .hybrid_index import HybridIndex, HybridIndexFactory
    from .vector_document_index import (
        default_brute_force_knn_document_index,
        default_lsh_knn_document_index,
        default_usearch_knn_document_index,
        default_vector_document_index,
    )
    from .retrievers import (
        AbstractRetrieverFactory,
        BruteForceKnnMetricKind,
        USearchMetricKind,
    )
except ImportError:  # pragma: no cover - during incremental build
    pass

from . import sorting

__all__ = [
    "DataIndex",
    "InnerIndex",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "TpuKnn",
    "TpuKnnFactory",
    "USearchKnn",
    "UsearchKnnFactory",
    "IvfKnn",
    "IvfKnnFactory",
    "LshKnn",
    "LshKnnFactory",
    "TantivyBM25",
    "TantivyBM25Factory",
    "BM25Index",
    "HybridIndex",
    "HybridIndexFactory",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_usearch_knn_document_index",
    "default_lsh_knn_document_index",
    "sorting",
]
