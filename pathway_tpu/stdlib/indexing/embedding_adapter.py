"""Adapter indexing *text* through an embedder + vector index pair.

Handles every embedder flavor: batched sync (one device call per
micro-batch — the TPU fast path), plain sync per-item, and async API
embedders (gathered on a private event loop)."""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, List, Sequence

import numpy as np

__all__ = ["EmbeddingIndexAdapter"]


class EmbeddingIndexAdapter:
    def __init__(self, inner, embedder):
        self.inner = inner
        self.embedder = embedder
        fn = embedder.func
        self._is_async = inspect.iscoroutinefunction(fn)
        self._is_batched = bool(getattr(embedder, "batched", False))

    def _embed(self, values: Sequence[Any]) -> List[np.ndarray]:
        texts = ["" if v is None else str(v) for v in values]
        fn = self.embedder.func
        if self._is_async:

            async def run():
                return await asyncio.gather(*(fn(t) for t in texts))

            out = asyncio.run(run())
        elif self._is_batched:
            arr = np.empty(len(texts), dtype=object)
            arr[:] = texts
            out = fn(arr)
        else:
            out = [fn(t) for t in texts]
        return [np.asarray(v, dtype=np.float32) for v in out]

    def add(self, keys, values, metadatas):
        if len(keys) == 0:
            return
        self.inner.add(keys, self._embed(values), metadatas)

    def remove(self, keys):
        self.inner.remove(keys)

    def search(self, values, k, filters):
        if len(values) == 0:
            return []
        return self.inner.search(self._embed(values), k, filters)
