"""Adapter indexing *text* through an embedder + vector index pair.

Handles every embedder flavor: batched sync (one device call per
micro-batch — the TPU fast path), plain sync per-item, and async API
embedders (gathered on a private event loop)."""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, List, Sequence

import numpy as np

__all__ = ["EmbeddingIndexAdapter"]


class EmbeddingIndexAdapter:
    def __init__(self, inner, embedder):
        self.inner = inner
        self.embedder = embedder
        if hasattr(embedder, "encode"):
            # model-object embedder (SentenceEncoder & friends): batched
            # list-of-strings -> [B, d] on device
            self._mode = "encode"
        else:
            fn = embedder.func  # UDF-style embedder
            self._mode = (
                "async"
                if inspect.iscoroutinefunction(fn)
                else "batched"
                if getattr(embedder, "batched", False)
                else "per_item"
            )

    def _embed(self, values: Sequence[Any]) -> List[np.ndarray]:
        texts = ["" if v is None else str(v) for v in values]
        if self._mode == "encode":
            return list(np.asarray(self.embedder.encode(texts), np.float32))  # pathway: allow(value-flow): ingest-side host materialization — the adapter's contract is host float32 rows for the inner index, one batched crossing per micro-batch, off every serve lock (mirrored in residency.DECLARED_TRANSFERS)
        fn = self.embedder.func
        if self._mode == "async":

            async def run():
                return await asyncio.gather(*(fn(t) for t in texts))

            out = asyncio.run(run())
        elif self._mode == "batched":
            arr = np.empty(len(texts), dtype=object)
            arr[:] = texts
            out = fn(arr)
        else:
            out = [fn(t) for t in texts]
        return [np.asarray(v, dtype=np.float32) for v in out]

    def add(self, keys, values, metadatas):
        if len(keys) == 0:
            return
        self.inner.add(keys, self._embed(values), metadatas)

    def remove(self, keys):
        self.inner.remove(keys)

    def search(self, values, k, filters):
        if len(values) == 0:
            return []
        return self.inner.search(self._embed(values), k, filters)
