"""Vector index implementations + factories
(reference: stdlib/indexing/nearest_neighbors.py:65-262 — USearchKnn,
BruteForceKnn, LshKnn wrappers over native indexes).

TPU-first: every dense variant is backed by the device-resident
``DeviceKnnIndex`` (ops/knn.py) — exact brute-force scoring on the MXU is the
operating point the reference reserves approximate HNSW for; ``TpuKnn``
additionally shards rows over the mesh.  The reference class names are kept
as aliases so templates/configs port unchanged."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...ops.knn import DeviceKnnIndex, normalize_metric
from .filters import compile_filter

__all__ = [
    "InnerIndexImpl",
    "DeviceKnn",
    "DeviceIvfKnn",
    "IvfKnn",
    "BruteForceKnn",
    "TpuKnn",
    "USearchKnn",
    "LshKnn",
    "BruteForceKnnFactory",
    "TpuKnnFactory",
    "UsearchKnnFactory",
    "IvfKnnFactory",
    "LshKnnFactory",
]


class InnerIndexImpl:
    """Protocol consumed by ExternalIndexOperator."""

    def add(self, keys: Sequence[int], values: Sequence[Any], metadatas: Sequence[Any]) -> None:
        raise NotImplementedError

    def remove(self, keys: Sequence[int]) -> None:
        raise NotImplementedError

    def search(
        self, values: Sequence[Any], k: int, filters: Sequence[Optional[str]]
    ) -> List[Tuple[Tuple[int, float], ...]]:
        raise NotImplementedError


class DeviceKnn(InnerIndexImpl):
    """Dense KNN on device with host-side metadata filtering
    (oversampled filtered search keeps scoring on the MXU)."""

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        mesh=None,
        initial_capacity: int = 1024,
        dtype=None,
    ):
        import jax.numpy as jnp

        self.index = DeviceKnnIndex(
            dimension=dimension,
            metric=metric,
            initial_capacity=initial_capacity,
            mesh=mesh,
            dtype=dtype or jnp.float32,
        )
        self.metadata: Dict[int, Any] = {}

    def add(self, keys, values, metadatas) -> None:
        vectors = np.array([np.asarray(v, dtype=np.float32) for v in values])
        self.index.add(keys, vectors)
        for key, md in zip(keys, metadatas):
            if md is not None:
                self.metadata[int(key)] = md

    def remove(self, keys) -> None:
        self.index.remove(keys)
        for key in keys:
            self.metadata.pop(int(key), None)

    def search(self, values, k, filters):
        vectors = np.array([np.asarray(v, dtype=np.float32) for v in values])
        if all(f is None for f in filters):
            rows = self.index.search(vectors, k)
            return [tuple(row) for row in rows]
        out: List[Tuple[Tuple[int, float], ...]] = []
        for vec, fexpr in zip(vectors, filters):
            if fexpr is None:
                out.append(tuple(self.index.search(vec[None, :], k)[0]))
                continue
            accept_fn = compile_filter(str(fexpr))
            rows = self.index.search_oversampled(
                vec[None, :],
                k,
                accept=lambda key: accept_fn(self.metadata.get(int(key), {})),
            )
            out.append(tuple(rows[0]))
        return out


class DeviceIvfKnn(DeviceKnn):
    """Approximate KNN for corpora past the exact index's comfort zone
    (>~1M rows): IVF probing with exact shortlist rescore (ops/ivf.py).
    Inherits DeviceKnn's add/remove/search incl. oversampled metadata
    filtering — IvfKnnIndex exposes the same host API as DeviceKnnIndex."""

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        n_clusters: Optional[int] = None,
        n_probe: Optional[int] = None,
    ):
        from ...ops.ivf import IvfKnnIndex

        self.index = IvfKnnIndex(
            dimension=dimension,
            metric=metric,
            n_clusters=n_clusters,
            n_probe=n_probe,
        )
        self.metadata: Dict[int, Any] = {}


# Factories (reference: stdlib/indexing/retrievers.py style factories used by
# DocumentStore/VectorStore; make() is called once per query operator)
class _DeviceKnnFactory:
    metric = "cos"
    sharded = False

    def __init__(
        self,
        dimension: Optional[int] = None,
        reserved_space: int = 1024,
        metric: Optional[str] = None,
        embedder=None,
        mesh=None,
        **kwargs,
    ):
        self.dimension = dimension
        self.reserved_space = reserved_space
        if metric is not None:
            self.metric = normalize_metric(metric)
        self.embedder = embedder
        self.mesh = mesh

    def build_inner_index(self, dimension: Optional[int] = None) -> DeviceKnn:
        dim = dimension or self.dimension
        if dim is None:
            raise ValueError("index factory needs the embedding dimension")
        mesh = self.mesh
        if self.sharded and mesh is None:
            from ...parallel import current_mesh

            mesh = current_mesh()
        inner = DeviceKnn(
            dimension=dim,
            metric=self.metric,
            mesh=mesh,
            initial_capacity=self.reserved_space,
        )
        if self.embedder is not None:
            # text columns are embedded (batched) at add/search time
            from .embedding_adapter import EmbeddingIndexAdapter

            return EmbeddingIndexAdapter(inner, self.embedder)
        return inner


class BruteForceKnnFactory(_DeviceKnnFactory):
    """Single-device exact KNN (reference BruteForceKnn,
    nearest_neighbors.py:170)."""


class TpuKnnFactory(_DeviceKnnFactory):
    """Mesh-sharded exact KNN: rows over the "data" axis, per-shard top-k +
    ICI all-gather merge (SURVEY.md §2.6)."""

    sharded = True


class UsearchKnnFactory(TpuKnnFactory):
    """Reference-name compatibility: the reference's approximate HNSW slot
    (nearest_neighbors.py:65) — on TPU the exact sharded index meets the same
    latency budget, so this is the same device index."""


class IvfKnnFactory(_DeviceKnnFactory):
    """Approximate IVF index (the reference's usearch-HNSW capability slot
    re-designed for TPU; ops/ivf.py).  Use for corpora where exact MXU
    scoring exceeds the latency budget (>~1M rows single chip)."""

    def __init__(self, *args, n_clusters=None, n_probe=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.n_clusters = n_clusters
        self.n_probe = n_probe

    def build_inner_index(self, dimension: Optional[int] = None):
        dim = dimension or self.dimension
        if dim is None:
            raise ValueError("index factory needs the embedding dimension")
        inner = DeviceIvfKnn(
            dimension=dim,
            metric=self.metric,
            n_clusters=self.n_clusters,
            n_probe=self.n_probe,
        )
        if self.embedder is not None:
            from .embedding_adapter import EmbeddingIndexAdapter

            return EmbeddingIndexAdapter(inner, self.embedder)
        return inner


class DeviceLshKnn(DeviceKnn):
    """Host-side LSH KNN (random-projection buckets + exact rescore) behind
    the InnerIndexImpl protocol (stdlib/ml/_knn_lsh.py)."""

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
    ):
        from ...stdlib.ml._knn_lsh import LshKnnIndex

        self.index = LshKnnIndex(
            dimension=dimension,
            metric=metric,
            n_or=n_or,
            n_and=n_and,
            bucket_length=bucket_length,
        )
        self.metadata: Dict[int, Any] = {}


class LshKnnFactory(_DeviceKnnFactory):
    """The reference's legacy LSH index (_knn_lsh.py:50-94), as a real
    random-projection implementation — not an exact-index alias."""

    def __init__(
        self, *args, n_or: int = 20, n_and: int = 10,
        bucket_length: float = 10.0, **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.n_or = n_or
        self.n_and = n_and
        self.bucket_length = bucket_length

    def build_inner_index(self, dimension: Optional[int] = None):
        dim = dimension or self.dimension
        if dim is None:
            raise ValueError("index factory needs the embedding dimension")
        inner = DeviceLshKnn(
            dimension=dim,
            metric=self.metric,
            n_or=self.n_or,
            n_and=self.n_and,
            bucket_length=self.bucket_length,
        )
        if self.embedder is not None:
            from .embedding_adapter import EmbeddingIndexAdapter

            return EmbeddingIndexAdapter(inner, self.embedder)
        return inner


# class-style aliases used by reference code/configs
BruteForceKnn = BruteForceKnnFactory
IvfKnn = IvfKnnFactory
TpuKnn = TpuKnnFactory
USearchKnn = UsearchKnnFactory
LshKnn = LshKnnFactory
