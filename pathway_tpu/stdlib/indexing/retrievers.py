"""Retriever factory surface (reference: stdlib/indexing/retrievers.py —
AbstractRetrieverFactory and metric kinds used by DocumentStore configs)."""

from __future__ import annotations

import enum

from .bm25 import TantivyBM25Factory
from .hybrid_index import HybridIndexFactory
from .nearest_neighbors import (
    BruteForceKnnFactory,
    LshKnnFactory,
    TpuKnnFactory,
    UsearchKnnFactory,
)

__all__ = [
    "AbstractRetrieverFactory",
    "BruteForceKnnMetricKind",
    "USearchMetricKind",
    "BruteForceKnnFactory",
    "TpuKnnFactory",
    "UsearchKnnFactory",
    "LshKnnFactory",
    "TantivyBM25Factory",
    "HybridIndexFactory",
]


class AbstractRetrieverFactory:
    def build_inner_index(self, dimension=None):
        raise NotImplementedError


class BruteForceKnnMetricKind(enum.Enum):
    """(reference: BruteForceKnnMetricKind, engine.pyi:869)"""

    COS = "cos"
    L2SQ = "l2sq"


class USearchMetricKind(enum.Enum):
    """(reference: USearchMetricKind, engine.pyi:854)"""

    COS = "cos"
    L2SQ = "l2sq"
    IP = "dot"
