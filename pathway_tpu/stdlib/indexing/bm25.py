"""Full-text BM25 index
(reference: stdlib/indexing/bm25.py:41 TantivyBM25 over the native tantivy
index, src/external_integration/tantivy_integration.rs:16).

Host-side incremental inverted index with Okapi BM25 scoring; retrieval is
candidate-set-bounded (union of query-term postings), so live updates stay
cheap.  The Tantivy* names are kept for config compatibility."""

from __future__ import annotations

import math
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .filters import compile_filter
from .nearest_neighbors import InnerIndexImpl

__all__ = ["BM25Index", "TantivyBM25", "TantivyBM25Factory"]

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(str(text).lower())


class BM25Index(InnerIndexImpl):
    def __init__(self, k1: float = 1.2, b: float = 0.75, ram_budget: Optional[int] = None):
        self.k1 = k1
        self.b = b
        self.postings: Dict[str, Dict[int, int]] = {}
        self.doc_tokens: Dict[int, Counter] = {}
        self.doc_len: Dict[int, int] = {}
        self.metadata: Dict[int, Any] = {}
        self.total_len = 0

    def add(self, keys, values, metadatas) -> None:
        for key, text, md in zip(keys, values, metadatas):
            key = int(key)
            if key in self.doc_tokens:
                self.remove([key])
            counts = Counter(_tokenize(text))
            self.doc_tokens[key] = counts
            n = sum(counts.values())
            self.doc_len[key] = n
            self.total_len += n
            for tok, tf in counts.items():
                self.postings.setdefault(tok, {})[key] = tf
            if md is not None:
                self.metadata[key] = md

    def remove(self, keys) -> None:
        for key in keys:
            key = int(key)
            counts = self.doc_tokens.pop(key, None)
            if counts is None:
                continue
            self.total_len -= self.doc_len.pop(key, 0)
            for tok in counts:
                plist = self.postings.get(tok)
                if plist is not None:
                    plist.pop(key, None)
                    if not plist:
                        del self.postings[tok]
            self.metadata.pop(key, None)

    def _score_query(self, text: str, k: int, accept=None) -> Tuple[Tuple[int, float], ...]:
        n_docs = len(self.doc_tokens)
        if n_docs == 0:
            return ()
        avg_len = self.total_len / n_docs
        scores: Dict[int, float] = {}
        for tok in set(_tokenize(text)):
            plist = self.postings.get(tok)
            if not plist:
                continue
            idf = math.log(1 + (n_docs - len(plist) + 0.5) / (len(plist) + 0.5))
            for doc, tf in plist.items():
                dl = self.doc_len[doc]
                denom = tf + self.k1 * (1 - self.b + self.b * dl / avg_len)
                scores[doc] = scores.get(doc, 0.0) + idf * tf * (self.k1 + 1) / denom
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
        out = []
        for doc, score in ranked:
            if accept is not None and not accept(self.metadata.get(doc, {})):
                continue
            out.append((doc, score))
            if len(out) >= k:
                break
        return tuple(out)

    def search(self, values, k, filters):
        out = []
        for text, fexpr in zip(values, filters):
            accept = compile_filter(str(fexpr)) if fexpr is not None else None
            out.append(self._score_query(text, k, accept))
        return out


class TantivyBM25Factory:
    """(reference: TantivyBM25 factory, bm25.py:41)"""

    def __init__(self, ram_budget: Optional[int] = None, in_memory_index: bool = True, **kwargs):
        self.ram_budget = ram_budget

    def build_inner_index(self, dimension: Optional[int] = None) -> BM25Index:
        return BM25Index(ram_budget=self.ram_budget)


TantivyBM25 = TantivyBM25Factory
