"""Default document-index builders
(reference: stdlib/indexing/vector_document_index.py:34-154 —
default_*_document_index helpers wiring an embedder + a KNN factory into a
DataIndex over (data_column, metadata_column))."""

from __future__ import annotations

from typing import Optional

from ...internals.expression import ColumnReference
from ...internals.table import Table
from .data_index import DataIndex, InnerIndex
from .nearest_neighbors import (
    BruteForceKnnFactory,
    LshKnnFactory,
    TpuKnnFactory,
    UsearchKnnFactory,
)

__all__ = [
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_usearch_knn_document_index",
    "default_lsh_knn_document_index",
]


def _make(
    factory_cls,
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: Optional[int] = None,
    embedder=None,
    metadata_column: Optional[ColumnReference] = None,
    **kwargs,
) -> DataIndex:
    if embedder is not None and dimensions is None:
        dimensions = embedder.get_embedding_dimension()
    factory = factory_cls(dimension=dimensions, embedder=embedder, **kwargs)
    inner = InnerIndex(
        data_column=data_column,
        metadata_column=metadata_column,
        factory=factory,
        dimension=dimensions,
    )
    return DataIndex(data_table, inner)


def default_vector_document_index(
    data_column: ColumnReference, data_table: Table, **kwargs
) -> DataIndex:
    return _make(TpuKnnFactory, data_column, data_table, **kwargs)


def default_brute_force_knn_document_index(
    data_column: ColumnReference, data_table: Table, **kwargs
) -> DataIndex:
    return _make(BruteForceKnnFactory, data_column, data_table, **kwargs)


def default_usearch_knn_document_index(
    data_column: ColumnReference, data_table: Table, **kwargs
) -> DataIndex:
    return _make(UsearchKnnFactory, data_column, data_table, **kwargs)


def default_lsh_knn_document_index(
    data_column: ColumnReference, data_table: Table, **kwargs
) -> DataIndex:
    return _make(LshKnnFactory, data_column, data_table, **kwargs)
