"""pw.viz — live table visualization
(reference: python/pathway/stdlib/viz/ — bokeh/panel streaming plots wired
to the update stream, plus Table._repr_mimebundle_ for notebooks).

The LIVE surface here is ``live_plot``: a zero-dependency dashboard —
a subscribe callback maintains the table's current state, a loopback HTTP
server serves a self-contained HTML page whose inline JS polls the JSON
snapshot and redraws an SVG chart while ``pw.run`` streams.  This is the
reference's bokeh/panel capability rebuilt for a headless TPU host where
those libraries are not bundled; ``plot``/``show`` additionally fall back
to matplotlib/text snapshots so notebook and script code stays importable
either way."""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Optional

__all__ = ["plot", "show", "table_snapshot", "live_plot", "LivePlotServer"]


def table_snapshot(table, limit: int = 20):
    """Current rows of a table as a list of dicts (post-run)."""
    keys, cols = table._materialize()
    names = list(cols)
    out = []
    for i, k in enumerate(keys[:limit]):
        row = {"id": int(k)}
        row.update({n: cols[n][i] for n in names})
        out.append(row)
    return out


def show(table, include_id: bool = True, limit: int = 20) -> None:
    """Print a snapshot of the table (reference: pw.Table.show / viz.show;
    with panel installed the reference renders a live widget — here a text
    table, which is what a headless TPU host can always do)."""
    rows = table_snapshot(table, limit)
    if not rows:
        print("<empty table>")
        return
    names = [n for n in rows[0] if include_id or n != "id"]
    widths = {
        n: max(len(str(n)), *(len(str(r[n])) for r in rows)) for n in names
    }
    header = " | ".join(str(n).ljust(widths[n]) for n in names)
    print(header)
    print("-" * len(header))
    for r in rows:
        print(" | ".join(str(r[n]).ljust(widths[n]) for n in names))


_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>pathway-tpu live plot</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 1.5rem; }}
 svg {{ border: 1px solid #ccc; background: #fafafa; }}
 table {{ border-collapse: collapse; margin-top: 1rem; font-size: 0.85rem; }}
 td, th {{ border: 1px solid #ddd; padding: 2px 8px; }}
 #meta {{ color: #666; font-size: 0.8rem; }}
</style></head>
<body>
<h3>{title}</h3>
<div id="meta"></div>
<svg id="chart" width="640" height="360" viewBox="0 0 640 360"></svg>
<table id="rows"></table>
<script>
const XCOL = {xcol!r}, YCOL = {ycol!r};
async function tick() {{
  try {{
    const resp = await fetch("/data");
    const body = await resp.json();
    render(body);
  }} catch (e) {{}}
  setTimeout(tick, 500);
}}
function render(body) {{
  const rows = body.rows;
  document.getElementById("meta").textContent =
    rows.length + " rows, updated " + new Date().toLocaleTimeString() +
    " (time " + body.time + ")";
  const svg = document.getElementById("chart");
  svg.innerHTML = "";
  const pts = rows
    .map(r => [Number(r[XCOL]), Number(r[YCOL])])
    .filter(p => isFinite(p[0]) && isFinite(p[1]))
    .sort((a, b) => a[0] - b[0]);
  if (pts.length) {{
    const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
    const x0 = Math.min(...xs), x1 = Math.max(...xs) || x0 + 1;
    const y0 = Math.min(...ys), y1 = Math.max(...ys) || y0 + 1;
    const sx = v => 40 + 580 * (v - x0) / ((x1 - x0) || 1);
    const sy = v => 330 - 300 * (v - y0) / ((y1 - y0) || 1);
    let d = "";
    pts.forEach((p, i) => {{
      d += (i ? "L" : "M") + sx(p[0]).toFixed(1) + "," + sy(p[1]).toFixed(1);
      const c = document.createElementNS("http://www.w3.org/2000/svg", "circle");
      c.setAttribute("cx", sx(p[0])); c.setAttribute("cy", sy(p[1]));
      c.setAttribute("r", 3); c.setAttribute("fill", "#2563eb");
      svg.appendChild(c);
    }});
    const path = document.createElementNS("http://www.w3.org/2000/svg", "path");
    path.setAttribute("d", d); path.setAttribute("stroke", "#93c5fd");
    path.setAttribute("fill", "none");
    svg.insertBefore(path, svg.firstChild);
  }}
  // build via textContent, never innerHTML: streamed string cells may carry
  // markup (user-supplied documents) and must not execute in the dashboard
  const tbl = document.getElementById("rows");
  tbl.replaceChildren();
  const names = rows.length ? Object.keys(rows[0]) : [];
  const head = document.createElement("tr");
  names.forEach(n => {{
    const th = document.createElement("th"); th.textContent = n;
    head.appendChild(th);
  }});
  tbl.appendChild(head);
  rows.slice(0, 25).forEach(r => {{
    const tr = document.createElement("tr");
    names.forEach(n => {{
      const td = document.createElement("td");
      td.textContent = String(r[n]);
      tr.appendChild(td);
    }});
    tbl.appendChild(tr);
  }});
}}
tick();
</script></body></html>
"""


class LivePlotServer:
    """Streams a table's CURRENT state to a browser: a subscribe callback
    maintains the snapshot incrementally (insertions/retractions), a
    loopback HTTP server serves / (self-contained SVG page) and /data
    (JSON).  The analog of the reference's bokeh streaming figure
    (stdlib/viz/), with zero extra dependencies."""

    def __init__(self, table, x: Optional[str], y: Optional[str], port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ...io._connector import jsonable
        from ...io._subscribe import subscribe

        names = table.column_names
        self.xcol = x or (names[0] if names else "")
        self.ycol = y or (names[1] if len(names) > 1 else self.xcol)
        self._lock = threading.Lock()
        self._rows: dict = {}
        self._time = 0

        def on_change(key, row, time, is_addition):
            with self._lock:
                if is_addition:
                    self._rows[int(key)] = {
                        n: jsonable(row[n]) for n in names
                    }
                else:
                    self._rows.pop(int(key), None)
                self._time = time

        subscribe(table, on_change=on_change)
        page = _PAGE.format(
            title=f"{table._short_name}: {self.ycol} over {self.xcol}",
            xcol=self.xcol,
            ycol=self.ycol,
        ).encode()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/data":
                    with outer._lock:
                        body = json.dumps(
                            {
                                "time": outer._time,
                                "rows": list(outer._rows.values()),
                            }
                        ).encode()
                    ctype = "application/json"
                elif self.path == "/":
                    body, ctype = page, "text/html"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        # loopback-bound, like the metrics server (round-1 advice)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="live-plot"
        ).start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"

    def snapshot(self) -> dict:
        with self._lock:
            return {"time": self._time, "rows": list(self._rows.values())}

    def close(self) -> None:
        self._httpd.shutdown()


def live_plot(
    table, *, x: Optional[str] = None, y: Optional[str] = None, port: int = 0
) -> LivePlotServer:
    """Serve a live-updating plot of ``table`` at the returned server's
    ``.url`` while the pipeline runs (reference: viz.plot + panel's
    streaming widget)."""
    return LivePlotServer(table, x, y, port)


def plot(
    table,
    plotting_function: Optional[Callable[..., Any]] = None,
    *,
    x: Optional[str] = None,
    y: Optional[str] = None,
    sorting_col: Optional[str] = None,
):
    """Plot a table column pair (reference: viz.plot wires a bokeh figure to
    the live update stream).  Uses bokeh when importable, else matplotlib
    (static snapshot), else raises with guidance."""
    rows = None
    try:
        import bokeh.plotting  # type: ignore  # pragma: no cover - not bundled

        have_bokeh = True
    except ImportError:
        have_bokeh = False
    if have_bokeh:  # pragma: no cover - bokeh not bundled in this image
        rows = table_snapshot(table, limit=10**6)
        if sorting_col:
            rows.sort(key=lambda r: r[sorting_col])
        if plotting_function is not None:
            # errors here (e.g. pandas missing) must surface — silently
            # dropping the user's plotting_function would be worse
            import pandas as pd

            from bokeh.models import ColumnDataSource

            return plotting_function(ColumnDataSource(pd.DataFrame(rows)))
        fig = bokeh.plotting.figure()
        names = [n for n in (rows[0] if rows else {}) if n != "id"]
        xcol = x or (names[0] if names else None)
        ycol = y or (names[1] if len(names) > 1 else xcol)
        if rows and xcol is not None:
            fig.scatter([r[xcol] for r in rows], [r[ycol] for r in rows])
        return fig
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "pw.viz.plot needs bokeh (live) or matplotlib (snapshot); "
            "neither is installed"
        ) from e
    if rows is None:
        rows = table_snapshot(table, limit=10**6)
        if sorting_col:
            rows.sort(key=lambda r: r[sorting_col])
    names = [n for n in (rows[0] if rows else {}) if n != "id"]
    xcol = x or (names[0] if names else None)
    ycol = y or (names[1] if len(names) > 1 else xcol)
    fig, ax = plt.subplots()
    if rows and xcol is not None:
        ax.plot([r[xcol] for r in rows], [r[ycol] for r in rows], marker="o")
        ax.set_xlabel(xcol)
        ax.set_ylabel(ycol)
    return fig
