"""pw.viz — live table visualization
(reference: python/pathway/stdlib/viz/ — bokeh/panel streaming plots wired
to the update stream, plus Table._repr_mimebundle_ for notebooks).

bokeh/panel are not bundled in this image, so the plotting surface is
gated: ``plot``/``show`` fall back to a text snapshot (and matplotlib for
``plot`` when available), keeping notebook and script code importable
either way."""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["plot", "show", "table_snapshot"]


def table_snapshot(table, limit: int = 20):
    """Current rows of a table as a list of dicts (post-run)."""
    keys, cols = table._materialize()
    names = list(cols)
    out = []
    for i, k in enumerate(keys[:limit]):
        row = {"id": int(k)}
        row.update({n: cols[n][i] for n in names})
        out.append(row)
    return out


def show(table, include_id: bool = True, limit: int = 20) -> None:
    """Print a snapshot of the table (reference: pw.Table.show / viz.show;
    with panel installed the reference renders a live widget — here a text
    table, which is what a headless TPU host can always do)."""
    rows = table_snapshot(table, limit)
    if not rows:
        print("<empty table>")
        return
    names = [n for n in rows[0] if include_id or n != "id"]
    widths = {
        n: max(len(str(n)), *(len(str(r[n])) for r in rows)) for n in names
    }
    header = " | ".join(str(n).ljust(widths[n]) for n in names)
    print(header)
    print("-" * len(header))
    for r in rows:
        print(" | ".join(str(r[n]).ljust(widths[n]) for n in names))


def plot(
    table,
    plotting_function: Optional[Callable[..., Any]] = None,
    *,
    x: Optional[str] = None,
    y: Optional[str] = None,
    sorting_col: Optional[str] = None,
):
    """Plot a table column pair (reference: viz.plot wires a bokeh figure to
    the live update stream).  Uses bokeh when importable, else matplotlib
    (static snapshot), else raises with guidance."""
    rows = None
    try:
        import bokeh.plotting  # type: ignore  # pragma: no cover - not bundled

        have_bokeh = True
    except ImportError:
        have_bokeh = False
    if have_bokeh:  # pragma: no cover - bokeh not bundled in this image
        rows = table_snapshot(table, limit=10**6)
        if sorting_col:
            rows.sort(key=lambda r: r[sorting_col])
        if plotting_function is not None:
            # errors here (e.g. pandas missing) must surface — silently
            # dropping the user's plotting_function would be worse
            import pandas as pd

            from bokeh.models import ColumnDataSource

            return plotting_function(ColumnDataSource(pd.DataFrame(rows)))
        fig = bokeh.plotting.figure()
        names = [n for n in (rows[0] if rows else {}) if n != "id"]
        xcol = x or (names[0] if names else None)
        ycol = y or (names[1] if len(names) > 1 else xcol)
        if rows and xcol is not None:
            fig.scatter([r[xcol] for r in rows], [r[ycol] for r in rows])
        return fig
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "pw.viz.plot needs bokeh (live) or matplotlib (snapshot); "
            "neither is installed"
        ) from e
    if rows is None:
        rows = table_snapshot(table, limit=10**6)
        if sorting_col:
            rows.sort(key=lambda r: r[sorting_col])
    names = [n for n in (rows[0] if rows else {}) if n != "id"]
    xcol = x or (names[0] if names else None)
    ycol = y or (names[1] if len(names) > 1 else xcol)
    fig, ax = plt.subplots()
    if rows and xcol is not None:
        ax.plot([r[xcol] for r in rows], [r[ycol] for r in rows], marker="o")
        ax.set_xlabel(xcol)
        ax.set_ylabel(ycol)
    return fig
