"""LSH KNN — random-projection bucketing with exact shortlist rescore.

The reference's legacy pure-dataflow index (stdlib/ml/_knn_lsh.py:50-94):
``n_or`` repetitions of ``n_and`` random hyperplane bits (cosine) or
quantized line projections (euclidean) map each vector to buckets; queries
union their buckets' members and rescore exactly.  Here the same scheme
runs host-side with numpy (bucket upkeep is dict work; the rescore is a
small dense matmul), conforming to the InnerIndexImpl protocol so it plugs
into DataIndex like the device indexes.

Operating guidance: DeviceKnnIndex (exact, MXU) and IvfKnnIndex (probed)
dominate this on TPU — LshKnn exists for reference API parity and for
host-only deployments."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["LshKnnIndex"]


class LshKnnIndex:
    """Same host API as DeviceKnnIndex: add / remove / search / len."""

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        seed: int = 0,
    ):
        from ...ops.knn import normalize_metric

        self.dimension = dimension
        self.metric = normalize_metric(metric)
        self.n_or = n_or
        self.n_and = n_and
        self.bucket_length = bucket_length
        self._lock = threading.RLock()
        rng = np.random.default_rng(seed)
        # [n_or, n_and, d] hyperplanes / projection lines
        self._planes = rng.normal(size=(n_or, n_and, dimension)).astype(
            np.float32
        )
        self._shifts = rng.uniform(0, bucket_length, size=(n_or, n_and)).astype(
            np.float32
        )
        self._rows: Dict[int, np.ndarray] = {}
        # per repetition: bucket signature -> set of keys
        self._buckets: List[Dict[bytes, set]] = [{} for _ in range(n_or)]

    def __len__(self) -> int:
        return len(self._rows)

    def _signatures(self, vectors: np.ndarray) -> np.ndarray:
        """[B, n_or] bucket signatures (bytes) per repetition."""
        proj = np.einsum("okd,bd->bok", self._planes, vectors)
        if self.metric in ("cos", "dot"):
            bits = (proj > 0).astype(np.uint8)  # hyperplane side
        else:  # euclidean: quantized line projection
            bits = np.floor(
                (proj + self._shifts[None]) / self.bucket_length
            ).astype(np.int64)
        B = vectors.shape[0]
        out = np.empty((B, self.n_or), dtype=object)
        for b in range(B):
            for o in range(self.n_or):
                out[b, o] = bits[b, o].tobytes()
        return out

    def add(self, keys: Sequence[int], vectors: np.ndarray) -> None:
        # coerce BEFORE the lock: callers hand the encoder's device rows
        # straight here, and the device→host sync must not run while
        # holding the bucket lock (value-flow analyzer finding)
        vectors = np.asarray(vectors, np.float32).reshape(
            len(keys), self.dimension
        )
        with self._lock:
            existing = [int(k) for k in keys if int(k) in self._rows]
            if existing:
                self.remove(existing)
            sigs = self._signatures(vectors)
            for i, key in enumerate(keys):
                key = int(key)
                self._rows[key] = vectors[i]
                for o in range(self.n_or):
                    self._buckets[o].setdefault(sigs[i, o], set()).add(key)

    def remove(self, keys: Sequence[int]) -> None:
        with self._lock:
            drop = [int(k) for k in keys if int(k) in self._rows]
            if not drop:
                return
            vectors = np.stack([self._rows[k] for k in drop])
            sigs = self._signatures(vectors)
            for i, key in enumerate(drop):
                del self._rows[key]
                for o in range(self.n_or):
                    bucket = self._buckets[o].get(sigs[i, o])
                    if bucket is not None:
                        bucket.discard(key)
                        if not bucket:
                            del self._buckets[o][sigs[i, o]]

    def search(
        self, queries: np.ndarray, k: int
    ) -> List[List[Tuple[int, float]]]:
        # same off-lock coercion rule as add(): a device-array query
        # batch syncs here, not under the lock
        queries = np.asarray(queries, np.float32).reshape(
            -1, self.dimension
        )
        with self._lock:
            if queries.shape[0] == 0 or not self._rows:
                return [[] for _ in range(queries.shape[0])]
            sigs = self._signatures(queries)
            out: List[List[Tuple[int, float]]] = []
            for qi in range(queries.shape[0]):
                candidates: set = set()
                for o in range(self.n_or):
                    candidates |= self._buckets[o].get(sigs[qi, o], set())
                if not candidates:
                    out.append([])
                    continue
                cand = sorted(candidates)
                mat = np.stack([self._rows[c] for c in cand])
                q = queries[qi]
                if self.metric == "cos":
                    denom = np.linalg.norm(mat, axis=1) * max(
                        np.linalg.norm(q), 1e-9
                    )
                    scores = (mat @ q) / np.where(denom == 0, 1.0, denom)
                elif self.metric == "dot":
                    scores = mat @ q
                else:  # l2sq ranking score: -squared distance
                    scores = -np.sum((mat - q[None, :]) ** 2, axis=1)
                order = np.argsort(-scores)[:k]
                out.append([(cand[j], float(scores[j])) for j in order])
            return out

    def search_oversampled(
        self, queries, k, accept, oversample: int = 4, max_rounds: int = 3
    ):
        from ...ops.knn import oversampled_filtered_search

        return oversampled_filtered_search(
            self, queries, k, accept, oversample=oversample, max_rounds=max_rounds
        )
