"""Fuzzy joins — probabilistic record matching between live tables
(reference: python/pathway/stdlib/ml/smart_table_ops/_fuzzy_join.py —
fuzzy_match_tables / smart_fuzzy_match / fuzzy_self_match / fuzzy_match;
feature generation :35-57, discrete normalizations :60-92, two-stage
argmax pair selection :410-470).

Rows are tokenized into features; a pair's weight is the sum over shared
features of a count-normalized feature weight (discretized so live count
changes rarely perturb weights); each left row then picks its best right
and each right keeps its best left (pseudoweight tie-break on ids, so the
matching is deterministic).  Everything is ordinary dataflow — the matching
updates incrementally as either table changes."""

from __future__ import annotations

import math
from enum import IntEnum, auto
from typing import Any, Callable, Dict, Optional

import numpy as np

from ...internals import api_reducers as reducers
from ...internals.expression import ApplyExpression, IdExpression, MakeTupleExpression
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = [
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match_tables",
    "smart_fuzzy_match",
    "fuzzy_self_match",
    "fuzzy_match",
]


def _tokenize(obj: Any):
    return tuple(str(obj).split())


def _letters(obj: Any):
    return tuple(c.lower() for c in str(obj) if c.isalnum())


class FuzzyJoinFeatureGeneration(IntEnum):
    AUTO = auto()
    TOKENIZE = auto()
    LETTERS = auto()

    @property
    def generate(self) -> Callable[[Any], Any]:
        if self == FuzzyJoinFeatureGeneration.LETTERS:
            return _letters
        return _tokenize


def _discrete_weight(cnt: float) -> float:
    if cnt <= 0:
        return 0.0
    return 1.0 / (2 ** math.ceil(math.log2(cnt)) if cnt > 1 else 1)


def _discrete_logweight(cnt: float) -> float:
    if cnt <= 0:
        return 0.0
    return 1.0 / math.ceil(math.log2(cnt + 1))


class FuzzyJoinNormalization(IntEnum):
    WEIGHT = auto()
    LOGWEIGHT = auto()
    NONE = auto()

    @property
    def normalize(self) -> Callable[[float], float]:
        if self == FuzzyJoinNormalization.WEIGHT:
            return _discrete_weight
        if self == FuzzyJoinNormalization.LOGWEIGHT:
            return _discrete_logweight
        return lambda cnt: float(cnt)


def _edges_for(table: Table, col, generation: FuzzyJoinFeatureGeneration) -> Table:
    gen = generation.generate
    with_feats = table.select(
        origin_id=IdExpression(table),
        feature=ApplyExpression(gen, None, args=(col,)),
    )
    return with_feats.flatten(with_feats.feature)


def smart_fuzzy_match(
    left_col,
    right_col,
    *,
    by_hand_match: Optional[Table] = None,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
) -> Table:
    """Match rows of two tables by a fuzzy comparison of one column each.

    Returns a table with columns ``left`` (pointer), ``right`` (pointer) and
    ``weight`` (float), one row per matched pair."""
    left = left_col.table
    right = right_col.table
    symmetric = left is right and left_col.name == right_col.name

    el = _edges_for(left, left_col, feature_generation)
    # self-match: a distinct table object for the right side so column
    # references resolve per side in the join (reference: edges_right =
    # edges_left.copy(), _fuzzy_join.py:353)
    er = el.copy() if symmetric else _edges_for(right, right_col, feature_generation)

    all_edges = el if symmetric else el.concat_reindex(er)
    feat_cnt = all_edges.groupby(id=all_edges.pointer_from(this.feature)).reduce(
        cnt=reducers.count()
    )
    norm = normalization.normalize
    feat_weight = feat_cnt.select(
        w=ApplyExpression(lambda c: norm(float(c)), None, args=(this.cnt,))
    )

    pairs = el.join(er, el.feature == er.feature).select(
        left=el.origin_id,
        right=er.origin_id,
        feature=el.feature,
    )
    if symmetric:
        pairs = pairs.filter(this.left != this.right)
    weighted = pairs.select(
        left=this.left,
        right=this.right,
        weight=feat_weight.ix(pairs.pointer_from(pairs.feature)).w,
    )
    summed = weighted.groupby(
        id=weighted.pointer_from(this.left, this.right)
    ).reduce(
        left=reducers.any(this.left),
        right=reducers.any(this.right),
        weight=reducers.sum(this.weight),
    )

    # pseudoweight orders pairs deterministically: (weight, min_id, max_id)
    def pseudo(w, l, r):
        a, b = (int(l), int(r)) if int(l) < int(r) else (int(r), int(l))
        return (float(w), a, b)

    scored = summed.select(
        left=this.left,
        right=this.right,
        pseudo=ApplyExpression(
            pseudo, None, args=(this.weight, this.left, this.right)
        ),
        weight=this.weight,
    )
    by_left = scored.groupby(id=this.left).reduce(
        left=reducers.any(this.left),
        right=reducers.argmax(
            this.pseudo,
            ApplyExpression(lambda r: np.uint64(r), None, args=(this.right,)),
        ),
        pseudo=reducers.max(this.pseudo),
    )
    by_right = by_left.groupby(id=this.right).reduce(
        right=reducers.any(this.right),
        left=reducers.argmax(
            this.pseudo,
            ApplyExpression(lambda l: np.uint64(l), None, args=(this.left,)),
        ),
        pseudo=reducers.max(this.pseudo),
    )
    matches = by_right.select(
        left=this.left,
        right=this.right,
        weight=ApplyExpression(lambda p: p[0], None, args=(this.pseudo,)),
    )
    if symmetric:
        matches = matches.filter(
            ApplyExpression(
                lambda l, r: int(l) < int(r), None, args=(this.left, this.right)
            )
        )
    if by_hand_match is not None:
        matches = matches.update_rows(
            by_hand_match.with_id_from(by_hand_match.right)
        )
    return matches


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    by_hand_match: Optional[Table] = None,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
    left_projection: Dict[str, str] = {},
    right_projection: Dict[str, str] = {},
) -> Table:
    """Fuzzy-match whole rows: columns are concatenated into one description
    per row (optionally bucketed by projections) and matched fuzzily
    (reference: fuzzy_match_tables, _fuzzy_join.py:106-176)."""

    def concat_desc(table: Table, columns=None) -> Table:
        cols = columns or table.column_names
        return table.select(
            desc=ApplyExpression(
                lambda *args: " ".join(str(a) for a in args),
                None,
                args=tuple(table[c] for c in cols),
            )
        )

    if not left_projection or not right_projection:
        l = concat_desc(left_table)
        r = concat_desc(right_table)
        return smart_fuzzy_match(
            l.desc,
            r.desc,
            by_hand_match=by_hand_match,
            normalization=normalization,
            feature_generation=feature_generation,
        )

    buckets: Dict[str, tuple] = {}
    for col, b in left_projection.items():
        buckets.setdefault(b, ([], []))[0].append(col)
    for col, b in right_projection.items():
        buckets.setdefault(b, ([], []))[1].append(col)
    partials = []
    for b, (lcols, rcols) in buckets.items():
        if not lcols or not rcols:
            continue
        l = concat_desc(left_table, lcols)
        r = concat_desc(right_table, rcols)
        partials.append(
            smart_fuzzy_match(
                l.desc,
                r.desc,
                by_hand_match=by_hand_match,
                normalization=normalization,
                feature_generation=feature_generation,
            )
        )
    if not partials:
        raise ValueError(
            "fuzzy_match_tables: left_projection and right_projection share "
            f"no bucket (left buckets {sorted(set(left_projection.values()))}, "
            f"right buckets {sorted(set(right_projection.values()))})"
        )
    merged = partials[0].concat_reindex(*partials[1:]) if len(partials) > 1 else partials[0]
    return merged.groupby(
        id=merged.pointer_from(this.left, this.right)
    ).reduce(
        left=reducers.any(this.left),
        right=reducers.any(this.right),
        weight=reducers.sum(this.weight),
    )


def fuzzy_self_match(col, **kwargs) -> Table:
    """Match rows of a table against itself (reference: fuzzy_self_match)."""
    return smart_fuzzy_match(col, col, **kwargs)


def fuzzy_match(left_col, right_col, **kwargs) -> Table:
    return smart_fuzzy_match(left_col, right_col, **kwargs)
