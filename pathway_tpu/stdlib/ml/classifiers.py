"""KNN classifiers (reference: stdlib/ml/classifiers.py +
_knn_lsh.py:64 knn_lsh_classifier_train — label voting over retrieved
neighbours)."""

from __future__ import annotations

from collections import Counter

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.table import Table
from .index import KNNIndex

__all__ = ["knn_classifier"]


def knn_classifier(
    data_embedding: ColumnReference,
    data: Table,
    label_column: ColumnReference,
    query_embedding: ColumnReference,
    n_dimensions: int,
    k: int = 3,
) -> Table:
    """Majority-vote label from the k nearest neighbours of each query."""
    index = KNNIndex(data_embedding, data, n_dimensions=n_dimensions)
    result = index._index.query(
        query_embedding, number_of_matches=k, collapse_rows=True
    )
    labels = result.select(_pw_labels=label_column)

    def vote(ls):
        ls = [l for l in ls if l is not None]
        if not ls:
            return None
        return Counter(ls).most_common(1)[0][0]

    from ...internals.thisclass import this

    return labels.select(
        predicted_label=ApplyExpression(vote, dt.ANY, args=(this._pw_labels,))
    )
