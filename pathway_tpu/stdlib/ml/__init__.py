"""pw.ml (reference: python/pathway/stdlib/ml/ — KNNIndex, LSH, classifiers,
smart_table_ops fuzzy joins)."""

from __future__ import annotations

from . import classifiers, hmm, index, smart_table_ops
from .hmm import create_hmm_reducer
from .index import KNNIndex
from .smart_table_ops import (
    FuzzyJoinFeatureGeneration,
    FuzzyJoinNormalization,
    fuzzy_match,
    fuzzy_match_tables,
    fuzzy_self_match,
    smart_fuzzy_match,
)

__all__ = [
    "index",
    "KNNIndex",
    "classifiers",
    "hmm",
    "create_hmm_reducer",
    "smart_table_ops",
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match",
    "fuzzy_match_tables",
    "fuzzy_self_match",
    "smart_fuzzy_match",
]
