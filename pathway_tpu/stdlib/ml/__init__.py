"""pw.ml (reference: python/pathway/stdlib/ml/ — KNNIndex, LSH, classifiers,
smart_table_ops).  Populated by the index milestone (index.py, _knn_lsh.py,
classifiers.py)."""

from __future__ import annotations

try:
    from . import index
    from .index import KNNIndex
except ImportError:  # pragma: no cover - during incremental build
    pass

try:
    from . import classifiers
except ImportError:  # pragma: no cover
    pass

__all__ = ["index", "KNNIndex", "classifiers"]
