"""Hidden-Markov-model decoding as a stateful reducer
(reference: python/pathway/stdlib/ml/hmm.py:11-210 — create_hmm_reducer
builds a custom accumulator running beam-searched Viterbi over a
networkx.DiGraph of states).

Graph contract (same as the reference): nodes carry a
``calc_emission_log_ppb(observation) -> float`` attribute, edges carry
``log_transition_ppb``; ``graph.graph["start_nodes"]`` lists entry states.
The returned reducer folds a group's observations (in arrival order — pair
with ``sort_by``/windowby for explicit ordering) and yields the most likely
state path as a tuple."""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ...internals import api_reducers as reducers

__all__ = ["create_hmm_reducer"]


def create_hmm_reducer(
    graph,
    beam_size: Optional[int] = None,
    num_results_kept: Optional[int] = None,
) -> Callable:
    """Returns a reducer expression factory: use as
    ``table.groupby(...).reduce(path=hmm_reducer(pw.this.observation))``."""
    nodes = list(graph.nodes)
    idx_of = {n: i for i, n in enumerate(nodes)}
    n_states = len(nodes)
    emit = [graph.nodes[n]["calc_emission_log_ppb"] for n in nodes]
    start_idx = [idx_of[n] for n in graph.graph["start_nodes"]]
    successors = [
        [
            (idx_of[m], graph.get_edge_data(n, m)["log_transition_ppb"])
            for m in graph.successors(n)
        ]
        for n in nodes
    ]
    beam = beam_size if beam_size is not None else n_states + 1

    def viterbi(observations) -> Optional[tuple]:
        if not observations:
            return None
        ppb = np.full(n_states, -np.inf)
        for i in start_idx:
            ppb[i] = emit[i](observations[0])
        live = list(start_idx)
        backpointers = []
        for obs in observations[1:]:
            new_ppb = np.full(n_states, -np.inf)
            back = np.full(n_states, -1, dtype=int)
            for src in live:
                base = ppb[src]
                for dst, logp in successors[src]:
                    cand = base + logp
                    if cand > new_ppb[dst]:
                        new_ppb[dst] = cand
                        back[dst] = src
            reached = np.flatnonzero(new_ppb > -np.inf)
            for dst in reached:
                new_ppb[dst] += emit[dst](obs)
            if len(reached) > beam:
                keep = reached[np.argpartition(new_ppb[reached], -beam)[-beam:]]
            else:
                keep = reached
            live = [int(i) for i in keep]
            if not live:
                return None  # no path continues
            backpointers.append(back)
            ppb = new_ppb
        best = int(np.argmax(ppb))
        path = [best]
        for back in reversed(backpointers):
            prev = int(back[path[-1]])
            if prev < 0:
                break
            path.append(prev)
        states = tuple(nodes[i] for i in reversed(path))
        if num_results_kept is not None:
            states = states[-num_results_kept:]
        return states

    def combine(_state: Any, rows) -> Optional[tuple]:
        return viterbi([r[0] for r in rows])

    return reducers.stateful_many(combine)
