"""Legacy KNNIndex API (reference: stdlib/ml/index.py:9 — KNNIndex with
get_nearest_items / get_nearest_items_asof_now over the LSH dataflow
implementation _knn_lsh.py).  Here it wraps the device DataIndex."""

from __future__ import annotations

from typing import Optional

from ...internals.expression import ColumnReference
from ...internals.table import Table
from ..indexing.data_index import DataIndex, InnerIndex
from ..indexing.nearest_neighbors import BruteForceKnnFactory, TpuKnnFactory

__all__ = ["KNNIndex"]


class KNNIndex:
    """K-nearest-neighbours over an embedding column of a live table."""

    def __init__(
        self,
        data_embedding: ColumnReference,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: Optional[ColumnReference] = None,
    ):
        metric = "l2sq" if distance_type == "euclidean" else "cos"
        self._metric = metric
        factory = TpuKnnFactory(
            dimension=n_dimensions, metric=metric, reserved_space=1024
        )
        self._index = DataIndex(
            data,
            InnerIndex(
                data_column=data_embedding,
                metadata_column=metadata,
                factory=factory,
                dimension=n_dimensions,
            ),
        )
        self._data = data

    def get_nearest_items(
        self,
        query_embedding: ColumnReference,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter=None,
    ):
        result = self._index.query(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        return self._project(result, collapse_rows, with_distances)

    def get_nearest_items_asof_now(
        self,
        query_embedding: ColumnReference,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter=None,
    ):
        result = self._index.query_as_of_now(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        return self._project(result, collapse_rows, with_distances)

    def _project(self, result, collapse_rows: bool, with_distances: bool) -> Table:
        cols = {
            name: ColumnReference(self._data, name)
            for name in self._data.column_names
        }
        out = result.select(
            **cols, **({"dist": result.score} if with_distances else {})
        )
        if with_distances:
            # ranking scores -> distances (ascending = closer), matching the
            # reference's dist column: cos -> 1 - sim; l2sq ranking score is
            # 2q.x - ||x||^2 which is monotone-decreasing in distance -> negate
            metric = self._metric
            from ...internals import dtype as dt_mod
            from ...internals.expression import ApplyExpression
            from ...internals.thisclass import this

            def to_dist(scores):
                if scores is None:
                    return scores
                if isinstance(scores, tuple):
                    return tuple(
                        (1.0 - s) if metric == "cos" else -s for s in scores
                    )
                return (1.0 - scores) if metric == "cos" else -scores

            out = out.with_columns(
                dist=ApplyExpression(to_dist, dt_mod.ANY, args=(this.dist,))
            )
        return out
