"""Wall-clock utilities: live "now" stream and inactivity detection
(reference: python/pathway/stdlib/temporal/time_utils.py — utc_now:31,
inactivity_detection:52-130).
"""

from __future__ import annotations

import datetime
import time
from typing import Optional, Tuple

from ...internals import api_reducers as reducers
from ...internals.expression import ApplyExpression
from ...internals.schema import Schema
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["utc_now", "inactivity_detection"]


class TimestampSchema(Schema):
    timestamp_utc: datetime.datetime


def utc_now(
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60),
    max_ticks: Optional[int] = None,
) -> Table:
    """A live single-row table holding the current UTC time, refreshed every
    ``refresh_rate`` (reference: utc_now, time_utils.py:31).  ``max_ticks``
    bounds the stream (used by tests and bounded runs — the engine's batch
    mode drains when all sources finish)."""
    from ...io.python import ConnectorSubject, read

    class _NowSubject(ConnectorSubject):
        def run(self) -> None:
            n = 0
            while max_ticks is None or n < max_ticks:
                now = datetime.datetime.now(datetime.timezone.utc)
                self.next(timestamp_utc=now)
                n += 1
                if max_ticks is not None and n >= max_ticks:
                    break
                time.sleep(refresh_rate.total_seconds())

    return read(_NowSubject(), schema=TimestampSchema, name="utc_now")


def inactivity_detection(
    event_time_column,
    allowed_inactivity_period: datetime.timedelta,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance=None,
    *,
    _now_table: Optional[Table] = None,
) -> Tuple[Table, Table]:
    """Flags inactivity gaps longer than ``allowed_inactivity_period`` and
    the first event resuming activity after each gap (reference:
    inactivity_detection, time_utils.py:52).

    Returns ``(inactivities, resumed_activities)``: tables with
    ``inactive_t`` / ``resumed_t`` (+ ``instance``) columns.  ``_now_table``
    overrides the clock stream (tests inject a deterministic one)."""
    events = event_time_column.table
    if instance is not None:
        events_t = events.select(t=event_time_column, instance=instance)
    else:
        events_t = events.select(
            t=event_time_column,
            instance=ApplyExpression(lambda _t: 0, None, args=(event_time_column,)),
        )

    now_t = _now_table if _now_table is not None else utc_now(refresh_rate)

    latest_t = events_t.groupby(this.instance).reduce(
        instance=this.instance, latest_t=reducers.max(this.t)
    )
    # every clock tick inspects the then-current latest event time; results
    # never retract (asof-now contract) so past alerts stay emitted
    joined = now_t.asof_now_join(latest_t).select(
        timestamp_utc=now_t.timestamp_utc,
        instance=latest_t.instance,
        latest_t=latest_t.latest_t,
    )
    import numpy as np

    # engine datetime columns are np.datetime64[ns]; plain timedelta doesn't
    # add to them, so normalise the allowed period once
    p64 = np.timedelta64(
        int(allowed_inactivity_period.total_seconds() * 1e9), "ns"
    )
    inactivities = (
        joined.filter(
            ApplyExpression(
                lambda latest, now, p=p64: (
                    latest is not None and latest + p < now
                ),
                None,
                args=(this.latest_t, this.timestamp_utc),
            )
        )
        .groupby(this.latest_t, this.instance)
        .reduce(instance=this.instance, inactive_t=this.latest_t)
    )

    latest_inactivity = inactivities.groupby(this.instance).reduce(
        instance=this.instance, inactive_t=reducers.latest(this.inactive_t)
    )
    resumed_activities = (
        events_t.asof_now_join(
            latest_inactivity, events_t.instance == latest_inactivity.instance
        )
        .select(
            t=events_t.t,
            instance=events_t.instance,
            inactive_t=latest_inactivity.inactive_t,
        )
        .filter(
            ApplyExpression(
                lambda t, inact: inact is not None and t > inact,
                None,
                args=(this.t, this.inactive_t),
            )
        )
        .groupby(this.inactive_t, this.instance)
        .reduce(instance=this.instance, resumed_t=reducers.min(this.t))
    )
    return inactivities, resumed_activities
