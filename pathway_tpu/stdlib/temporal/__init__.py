"""pw.temporal — windows, temporal joins, behaviors
(reference: python/pathway/stdlib/temporal/ — _window.py:42-865,
_interval_join.py, _asof_join.py, _window_join.py, temporal_behavior.py).

Windows desugar to key extension + groupby (the reference's own lowering:
window instance becomes part of the group key, _window.py:865).  Interval and
window joins desugar to bucket-explosion (flatten) + equi-join + bound filter
— fully incremental because each stage is.
"""

from __future__ import annotations

import datetime
import math
from dataclasses import dataclass
from typing import Any, Optional, Union

from ...internals import dtype as dt
from ...internals.expression import (
    ApplyExpression,
    ColumnExpression,
    MethodCallExpression,
    smart_coerce,
)
from ...internals.table import JoinMode, Table
from ...internals.thisclass import this
from .temporal_behavior import Behavior, CommonBehavior, ExactlyOnceBehavior, common_behavior, exactly_once_behavior

__all__ = [
    "Window",
    "tumbling",
    "sliding",
    "session",
    "intervals_over",
    "windowby",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
    "asof_join",
    "asof_join_left",
    "asof_join_right",
    "asof_join_outer",
    "asof_now_join",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "Behavior",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "common_behavior",
    "exactly_once_behavior",
]


def _num(v: Any) -> float:
    if isinstance(v, datetime.timedelta):
        return v.total_seconds()
    if isinstance(v, (datetime.datetime,)):
        return v.timestamp()
    return v


class Window:
    pass


@dataclass
class TumblingWindow(Window):
    duration: Any
    origin: Any = None

    def assign(self, t: Any):
        d = _num(self.duration)
        o = _num(self.origin) if self.origin is not None else 0.0
        start = math.floor((_num(t) - o) / d) * d + o
        return [(start, start + d)]


@dataclass
class SlidingWindow(Window):
    hop: Any
    duration: Optional[Any] = None
    ratio: Optional[int] = None
    origin: Any = None

    def assign(self, t: Any):
        hop = _num(self.hop)
        dur = _num(self.duration) if self.duration is not None else hop * self.ratio
        o = _num(self.origin) if self.origin is not None else 0.0
        tv = _num(t)
        out = []
        # windows [s, s+dur) with s = o + k*hop containing tv, largest k first
        k = math.floor((tv - o) / hop)
        while True:
            s = o + k * hop
            if s + dur <= tv:
                break
            out.append((s, s + dur))
            k -= 1
        return list(reversed(out))


@dataclass
class SessionWindow(Window):
    predicate: Optional[Any] = None
    max_gap: Optional[Any] = None


@dataclass
class IntervalsOverWindow(Window):
    at: Any
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def tumbling(duration: Any, origin: Any = None) -> TumblingWindow:
    """(reference: _window.py tumbling)"""
    return TumblingWindow(duration=duration, origin=origin)


def sliding(
    hop: Any, duration: Optional[Any] = None, ratio: Optional[int] = None, origin: Any = None
) -> SlidingWindow:
    return SlidingWindow(hop=hop, duration=duration, ratio=ratio, origin=origin)


def session(*, predicate=None, max_gap=None) -> SessionWindow:
    return SessionWindow(predicate=predicate, max_gap=max_gap)


def intervals_over(*, at, lower_bound, upper_bound, is_outer: bool = True) -> IntervalsOverWindow:
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


def _apply_behavior(flat, behavior):
    """Desugar a window behavior into a time gate on the flattened
    (window-assigned) rows (reference lowering: _window.py behaviors →
    buffer/forget engine ops, time_column.rs:380,677):

    - ``CommonBehavior.delay d``   — hold each row until the stream clock
      passes ``window_start + d`` (postpone).
    - ``CommonBehavior.cutoff c``  — drop rows once the clock passed
      ``window_end + c`` (ignore_late); the paired sweeper forgets group
      state past the same threshold, and with ``keep_results=False`` also
      retracts the frozen result rows.
    - ``ExactlyOnceBehavior(shift s)`` — release == expire ==
      ``window_end + s``: every window emits exactly once, then freezes.

    Returns (gated_table, gate_operator | None, expire_of(group_values) | None).
    """
    if behavior is None:
        return flat, None, None
    if isinstance(behavior, ExactlyOnceBehavior):
        shift = _num(behavior.shift) if behavior.shift is not None else 0.0
        thr = ApplyExpression(
            lambda e, s=shift: e + s, dt.FLOAT, args=(this._pw_window_end,)
        )
        gated, gate = flat._time_gate(this._pw_time, thr, thr)
        return gated, gate, (lambda end, s=shift: end + s)
    if isinstance(behavior, CommonBehavior):
        release = expire = expire_of = None
        if behavior.delay is not None:
            d = _num(behavior.delay)
            release = ApplyExpression(
                lambda st, d=d: st + d, dt.FLOAT, args=(this._pw_window_start,)
            )
        if behavior.cutoff is not None:
            c = _num(behavior.cutoff)
            expire = ApplyExpression(
                lambda e, c=c: e + c, dt.FLOAT, args=(this._pw_window_end,)
            )
            expire_of = lambda end, c=c: end + c  # noqa: E731
        if release is None and expire is None:
            return flat, None, None
        gated, gate = flat._time_gate(this._pw_time, release, expire)
        return gated, gate, expire_of
    raise TypeError(f"unsupported window behavior: {behavior!r}")


def _window_end_index(gop) -> int:
    """Position of the window-end value inside the groupby's group-values
    tuple — the reduce lowering may fold/rename grouping columns, so locate
    it by the underlying column reference, not by position."""
    from ...internals.expression import ColumnReference

    for i, (name, e) in enumerate(gop.grouping_expressions.items()):
        if name == "_pw_window_end" or (
            isinstance(e, ColumnReference) and e.name == "_pw_window_end"
        ):
            return i
    raise RuntimeError(
        "windowed groupby lost its _pw_window_end grouping column"
    )


def _groupby_sweeper(gop, expire_of, retract: bool):
    """Sweep hook forgetting expired window groups (reference
    Graph::forget/freeze, src/engine/graph.rs:776-812).  State for windows
    whose expiry the (lagged) clock passed is dropped — streaming state stays
    bounded — and with ``retract`` the frozen results are withdrawn too
    (keep_results=False)."""
    from ...engine.delta import Delta

    end_idx = _window_end_index(gop)

    def sweep(clock):
        expired = [
            gk
            for gk, entry in gop._groups.items()
            if expire_of(entry[1][end_idx]) <= clock
        ]
        if not expired:
            return None
        rows = []
        for gk in expired:
            del gop._groups[gk]
            if retract:
                old = gop.output.store.get(gk)
                if old is not None:
                    rows.append((gk, -1, old))
        if not rows:
            return None
        return (gop.output, Delta.from_rows(gop.output.column_names, rows))

    return sweep


class WindowedTable:
    """Result of windowby(): a GroupedTable whose group key includes the
    window instance; exposes _pw_window_start/_pw_window_end columns."""

    def __init__(self, table: Table, key_expr, window: Window, instance=None, behavior=None):
        self.table = table
        self.key_expr = key_expr
        self.window = window
        self.instance = instance
        self.behavior = behavior

    def reduce(self, *args, **kwargs) -> Table:
        win = self.window
        if isinstance(win, (TumblingWindow, SlidingWindow)):
            flat = self.table.with_columns(
                _pw_window=ApplyExpression(
                    win.assign, dt.ANY, args=(self.key_expr,)
                ),
                _pw_time=self.key_expr,
            ).flatten(this._pw_window)
            flat = flat.with_columns(
                _pw_window_start=ApplyExpression(
                    lambda w: w[0], dt.FLOAT, args=(this._pw_window,)
                ),
                _pw_window_end=ApplyExpression(
                    lambda w: w[1], dt.FLOAT, args=(this._pw_window,)
                ),
            )
            flat, gate, expire_of = _apply_behavior(flat, self.behavior)
            grouping = [flat._pw_window_start, flat._pw_window_end]
            if self.instance is not None:
                inst = self.instance
                if isinstance(inst, ColumnExpression):
                    grouping.append(inst)
            grouped = flat.groupby(*grouping)
            out = grouped.reduce(*args, **kwargs)
            if gate is not None and expire_of is not None:
                gate.sweep_hooks.append(
                    _groupby_sweeper(
                        out._engine_table.producer,
                        expire_of,
                        retract=isinstance(self.behavior, CommonBehavior)
                        and not self.behavior.keep_results,
                    )
                )
            return out
        if isinstance(win, SessionWindow):
            return self._reduce_session(*args, **kwargs)
        if isinstance(win, IntervalsOverWindow):
            return self._reduce_intervals_over(*args, **kwargs)
        raise NotImplementedError(type(win))

    def _reduce_session(self, *args, **kwargs) -> Table:
        from .session_windows import reduce_session

        return reduce_session(self, *args, **kwargs)

    def _reduce_intervals_over(self, *args, **kwargs) -> Table:
        win = self.window
        lb, ub = _num(win.lower_bound), _num(win.upper_bound)
        at_table_refs = [
            r for r in smart_coerce(win.at)._column_refs() if isinstance(r.table, Table)
        ]
        if not at_table_refs:
            raise ValueError("intervals_over: `at` must be a column reference")
        at_table = at_table_refs[0].table
        # data rows join at-locations via bucket explosion of the at side
        B = ub - lb if ub > lb else 1.0

        def buckets_of_at(t):
            t = _num(t)
            lo = math.floor((t + lb) / B)
            hi = math.floor((t + ub) / B)
            return [b for b in range(lo, hi + 1)]

        def bucket_of_data(t):
            return math.floor(_num(t) / B)

        at_flat = at_table.select(_pw_at=smart_coerce(win.at)).with_columns(
            _pw_bucket=ApplyExpression(buckets_of_at, dt.ANY, args=(this._pw_at,))
        ).flatten(this._pw_bucket)
        data = self.table.with_columns(
            _pw_bucket=ApplyExpression(bucket_of_data, dt.INT, args=(self.key_expr,)),
            _pw_key=self.key_expr,
        )
        # inner-join + exact bound filter: aggregates only see real rows
        joined = at_flat.join(data, at_flat._pw_bucket == data._pw_bucket)
        cols = {n: getattr(data, n) for n in self.table.column_names}
        sel = joined.select(
            _pw_window_location=at_flat._pw_at, _pw_key=data._pw_key, **cols
        )
        filtered = sel.filter(
            ApplyExpression(
                lambda at, t: t is not None
                and _num(at) + lb <= _num(t) <= _num(at) + ub,
                dt.BOOL,
                args=(this._pw_window_location, this._pw_key),
            )
        )
        grouped = filtered.groupby(filtered._pw_window_location)
        matched = grouped.reduce(*args, **kwargs)
        if not win.is_outer:
            return matched
        # outer: at-locations with no data still appear, aggregates = None
        # (reference intervals_over is_outer semantics, _window.py)
        at_keyed = at_table.select(_pw_at=smart_coerce(win.at)).with_id_from(
            this._pw_at
        )
        empty = at_keyed.difference(matched)
        out_exprs: dict = {}
        for arg in args:
            out_exprs[arg.name] = arg
        out_exprs.update(kwargs)
        from ...internals.expression import ColumnConstExpression, ColumnReference

        padded_exprs = {}
        for name, e in out_exprs.items():
            if isinstance(e, ColumnReference) and e.name == "_pw_window_location":
                padded_exprs[name] = empty._pw_at
            else:
                padded_exprs[name] = ColumnConstExpression(None)
        padded = empty.select(**padded_exprs)
        return matched.concat(padded)


def windowby(
    table: Table,
    time_expr,
    *,
    window: Window,
    instance=None,
    behavior: Optional[Behavior] = None,
    **kwargs,
) -> WindowedTable:
    """(reference: _window.py:865 windowby)"""
    return WindowedTable(table, smart_coerce(time_expr), window, instance, behavior)


# ---------------------------------------------------------------------------
# interval joins (reference: _interval_join.py)
# ---------------------------------------------------------------------------
@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    return Interval(lower_bound, upper_bound)


def _interval_join_impl(
    left: Table,
    right: Table,
    left_time,
    right_time,
    itv: Interval,
    *on,
    how: str = JoinMode.INNER,
    behavior: Optional[Behavior] = None,
) -> "IntervalJoinResult":
    return IntervalJoinResult(
        left, right, left_time, right_time, itv, on, how, behavior=behavior
    )


class IntervalJoinResult:
    """left.t + lb <= right.t <= left.t + ub
    — bucket-explode left over the buckets covering its interval, equi-join on
    bucket (+ extra on conditions), filter exact bounds; LEFT/RIGHT/OUTER pad
    unmatched rows with None via key-difference against the matched set
    (reference: stdlib/temporal/_interval_join.py)."""

    def __init__(self, left, right, left_time, right_time, itv, on, how, behavior=None):
        from ...internals.expression import IdExpression

        lb, ub = _num(itv.lower_bound), _num(itv.upper_bound)
        if ub < lb:
            raise ValueError("interval: upper bound below lower bound")
        B = max(ub - lb, 1e-9)

        def left_buckets(t):
            t = _num(t)
            lo = math.floor((t + lb) / B)
            hi = math.floor((t + ub) / B)
            return list(range(lo, hi + 1))

        def right_bucket(t):
            return math.floor(_num(t) / B)

        lflat = left.with_columns(
            _pw_lbuckets=ApplyExpression(left_buckets, dt.ANY, args=(left_time,)),
            _pw_lt=smart_coerce(left_time),
            _pw_lid=IdExpression(None),
        ).flatten(this._pw_lbuckets)
        rtab = right.with_columns(
            _pw_rbucket=ApplyExpression(right_bucket, dt.INT, args=(right_time,)),
            _pw_rt=smart_coerce(right_time),
            _pw_rid=IdExpression(None),
        )
        self._gated = False
        if behavior is not None:
            gated_pair = self._gate_sides(lflat, rtab, behavior, lb, ub)
            self._gated = gated_pair != (lflat, rtab)
            lflat, rtab = gated_pair
        conds = [lflat._pw_lbuckets == rtab._pw_rbucket]
        for cond in on:
            lref, rref = cond._left, cond._right
            conds.append(getattr(lflat, lref.name) == getattr(rtab, rref.name))
        self._join = lflat.join(rtab, *conds, how=JoinMode.INNER)
        self._lflat = lflat
        self._rtab = rtab
        self._left = left
        self._right = right
        self._lb, self._ub = lb, ub
        self._how = how

    @staticmethod
    def _gate_sides(lflat, rtab, behavior, lb, ub):
        """Behavior on an interval join: both inputs share one clock
        (reference: the global input frontier); ``delay`` holds a row until
        clock >= t + delay, ``cutoff`` drops a row once it can no longer
        match any on-time opposite row — left expires at t + ub + cutoff,
        right at t - lb + cutoff (reference _interval_join.py behavior
        thresholds over time_column.rs buffers)."""
        if not isinstance(behavior, CommonBehavior):
            raise TypeError(
                f"interval_join supports common_behavior only, got {behavior!r}"
            )
        from ...engine.operators.time_gate import SharedClock

        d = _num(behavior.delay) if behavior.delay is not None else None
        c = _num(behavior.cutoff) if behavior.cutoff is not None else None
        if d is None and c is None:
            return lflat, rtab
        clock = SharedClock()

        def gate(tab, tref, expire_offset):
            time_e = ApplyExpression(lambda t: _num(t), dt.FLOAT, args=(tref,))
            rel = (
                ApplyExpression(
                    lambda t, d=d: _num(t) + d, dt.FLOAT, args=(tref,)
                )
                if d is not None
                else None
            )
            exp = (
                ApplyExpression(
                    lambda t, o=expire_offset: _num(t) + o,
                    dt.FLOAT,
                    args=(tref,),
                )
                if c is not None
                else None
            )
            gated, _op = tab._time_gate(time_e, rel, exp, clock=clock)
            return gated

        lflat = gate(lflat, this._pw_lt, (ub + c) if c is not None else None)
        rtab = gate(rtab, this._pw_rt, (c - lb) if c is not None else None)
        return lflat, rtab

    def select(self, *args, **kwargs) -> Table:
        lb, ub = self._lb, self._ub
        exprs = {}
        for arg in args:
            exprs[arg.name] = arg
        exprs.update(kwargs)
        out_names = list(exprs.keys())
        remapped = {
            name: _remap(
                e, {id(self._left): self._lflat, id(self._right): self._rtab}
            )
            for name, e in exprs.items()
        }
        full = self._join.select(
            _pw_lt2=self._lflat._pw_lt,
            _pw_rt2=self._rtab._pw_rt,
            _pw_lid2=self._lflat._pw_lid,
            _pw_rid2=self._rtab._pw_rid,
            **remapped,
        )
        matched = full.filter(
            ApplyExpression(
                lambda lt, rt: _num(lt) + lb <= _num(rt) <= _num(lt) + ub,
                dt.BOOL,
                args=(this._pw_lt2, this._pw_rt2),
            )
        )
        helper = ["_pw_lt2", "_pw_rt2", "_pw_lid2", "_pw_rid2"]
        parts = [matched.without(*helper)]
        if self._how in (JoinMode.LEFT, JoinMode.OUTER):
            matched_left_keys = matched.select(_pw_m=this._pw_lid2).with_id(
                this._pw_m
            )
            # pad only rows that SURVIVED the behavior gate: a cutoff-dropped
            # or still-buffered row must not leak out as an unmatched pad
            left_alive = self._left
            if self._gated:
                gated_ids = self._lflat.select(_pw_m=this._pw_lid).with_id(
                    this._pw_m
                )
                left_alive = self._left.intersect(gated_ids)
            unmatched = left_alive.difference(matched_left_keys)
            parts.append(
                unmatched.select(
                    **{
                        name: _remap(
                            e,
                            {id(self._left): unmatched},
                            null_tables={id(self._right), id(self._rtab)},
                        )
                        for name, e in exprs.items()
                    }
                )
            )
        if self._how in (JoinMode.RIGHT, JoinMode.OUTER):
            matched_right_keys = matched.select(_pw_m=this._pw_rid2).with_id(
                this._pw_m
            )
            right_alive = self._right
            if self._gated:
                gated_rids = self._rtab.select(_pw_m=this._pw_rid).with_id(
                    this._pw_m
                )
                right_alive = self._right.intersect(gated_rids)
            unmatched = right_alive.difference(matched_right_keys)
            parts.append(
                unmatched.select(
                    **{
                        name: _remap(
                            e,
                            {id(self._right): unmatched},
                            null_tables={id(self._left), id(self._lflat)},
                        )
                        for name, e in exprs.items()
                    }
                )
            )
        if len(parts) == 1:
            return parts[0]
        return parts[0].concat_reindex(*parts[1:])


def _remap(expr, table_map, null_tables=None):
    """Rebind column references from original tables onto derived tables;
    references to tables in ``null_tables`` become None constants (used to
    pad the missing side of outer temporal joins)."""
    from ...internals.expression import ColumnConstExpression, ColumnReference

    null_tables = null_tables or set()
    if isinstance(expr, ColumnReference):
        if id(expr.table) in null_tables:
            return ColumnConstExpression(None)
        t = table_map.get(id(expr.table))
        if t is not None:
            return getattr(t, expr.name)
        return expr
    if not isinstance(expr, ColumnExpression):
        return expr
    # rebuild by shallow-copying and remapping deps
    import copy

    new = copy.copy(expr)
    for attr, value in list(vars(new).items()):
        if isinstance(value, ColumnExpression):
            setattr(new, attr, _remap(value, table_map, null_tables))
        elif isinstance(value, tuple) and any(
            isinstance(v, ColumnExpression) for v in value
        ):
            setattr(
                new,
                attr,
                tuple(
                    _remap(v, table_map, null_tables)
                    if isinstance(v, ColumnExpression)
                    else v
                    for v in value
                ),
            )
    new._deps = tuple(
        _remap(d, table_map, null_tables) if isinstance(d, ColumnExpression) else d
        for d in new._deps
    )
    return new


def interval_join(left, right, left_time, right_time, itv, *on, behavior=None, how=JoinMode.INNER):
    return _interval_join_impl(
        left, right, left_time, right_time, itv, *on, how=how, behavior=behavior
    )


def interval_join_inner(left, right, left_time, right_time, itv, *on, behavior=None, **kw):
    return _interval_join_impl(
        left, right, left_time, right_time, itv, *on,
        how=JoinMode.INNER, behavior=behavior,
    )


def interval_join_left(left, right, left_time, right_time, itv, *on, behavior=None, **kw):
    return _interval_join_impl(
        left, right, left_time, right_time, itv, *on,
        how=JoinMode.LEFT, behavior=behavior,
    )


def interval_join_right(left, right, left_time, right_time, itv, *on, behavior=None, **kw):
    return _interval_join_impl(
        left, right, left_time, right_time, itv, *on,
        how=JoinMode.RIGHT, behavior=behavior,
    )


def interval_join_outer(left, right, left_time, right_time, itv, *on, behavior=None, **kw):
    return _interval_join_impl(
        left, right, left_time, right_time, itv, *on,
        how=JoinMode.OUTER, behavior=behavior,
    )


# ---------------------------------------------------------------------------
# asof joins (reference: _asof_join.py:1107)
# ---------------------------------------------------------------------------
class AsofJoinResult:
    """For each left row, match the latest right row with right.t <= left.t
    (direction configurable).  Implemented as groupby-side accumulation: the
    right side is reduced to sorted tuples per join key, and each left row
    binary-searches at select time — incremental because the sorted tuple is."""

    def __init__(self, left, right, left_time, right_time, on, how, direction="backward"):
        from ...internals import api_reducers as reducers
        from ...internals.thisclass import left as left_ph
        from ...internals.thisclass import right as right_ph

        self._how = how

        def side_of(e):
            for ref in smart_coerce(e)._column_refs():
                if ref.table is left or ref.table is left_ph:
                    return "left"
                if ref.table is right or ref.table is right_ph:
                    return "right"
            return None

        lkeys, rkeys = [], []
        for c in on:
            a, b = c._left, c._right
            if side_of(a) == "right" or side_of(b) == "left":
                a, b = b, a
            lkeys.append(a)
            rkeys.append(b)

        rt = right.with_columns(_pw_rt=smart_coerce(right_time))
        # packed columns named after the LEFT key names so select-time join
        # conditions line up regardless of differing column names
        if rkeys:
            grouped = rt.groupby(*[getattr(rt, k.name) for k in rkeys])
            gcols = {
                lk.name: getattr(rt, rk.name) for lk, rk in zip(lkeys, rkeys)
            }
        else:
            grouped = rt.groupby()
            gcols = {}
        packed = grouped.reduce(
            **gcols,
            _pw_rows=reducers.sorted_tuple(
                ApplyExpression(
                    lambda t, *vals: (_num(t), vals),
                    dt.ANY,
                    args=(rt._pw_rt, *[getattr(rt, c) for c in right.column_names]),
                )
            ),
        )
        self._left = left
        self._right = right
        self._packed = packed
        self._left_time = left_time
        self._lkeys = lkeys
        self._direction = direction
        self._right_names = list(right.column_names)

    def select(self, *args, **kwargs) -> Table:
        import bisect

        direction = self._direction
        right_names = self._right_names

        def lookup(rows, t):
            if rows is None:
                return None
            t = _num(t)
            times = [r[0] for r in rows]
            if direction in ("backward",):
                i = bisect.bisect_right(times, t) - 1
                return rows[i][1] if i >= 0 else None
            else:
                i = bisect.bisect_left(times, t)
                return rows[i][1] if i < len(rows) else None

        left = self._left
        if self._lkeys:
            conds = [
                getattr(left, lk.name) == getattr(self._packed, lk.name)
                for lk in self._lkeys
            ]
        else:
            # keyless asof: every left row joins the single global packed row
            conds = [smart_coerce(0) == smart_coerce(0)]
        jr = left.join(self._packed, *conds, how=JoinMode.LEFT)
        matched = jr.select(
            *[getattr(left, c) for c in left.column_names],
            _pw_match=ApplyExpression(
                lookup, dt.ANY, args=(self._packed._pw_rows, self._left_time)
            ),
        )
        exprs = {}
        for arg in args:
            exprs[arg.name] = arg
        exprs.update(kwargs)
        out_exprs = {}
        for name, e in exprs.items():
            out_exprs[name] = _remap_asof(e, left, matched, right_names)
        result = matched.select(**out_exprs)
        if self._how == JoinMode.INNER:
            # refilter unmatched
            keep = matched.filter(
                ApplyExpression(lambda m: m is not None, dt.BOOL, args=(this._pw_match,))
            )
            result = result.restrict(keep)
        return result


def _remap_asof(expr, left, matched, right_names):
    from ...internals.expression import ColumnReference

    if isinstance(expr, ColumnReference):
        if expr.name in right_names and (
            not isinstance(expr.table, Table) or expr.table is not left
        ):
            idx = right_names.index(expr.name)
            return ApplyExpression(
                lambda m, _i=idx: m[_i] if m is not None else None,
                dt.ANY,
                args=(getattr(matched, "_pw_match"),),
            )
        if isinstance(expr.table, Table) and expr.table is not left:
            idx = right_names.index(expr.name)
            return ApplyExpression(
                lambda m, _i=idx: m[_i] if m is not None else None,
                dt.ANY,
                args=(getattr(matched, "_pw_match"),),
            )
        return getattr(matched, expr.name)
    if not isinstance(expr, ColumnExpression):
        return expr
    import copy

    new = copy.copy(expr)
    for attr, value in list(vars(new).items()):
        if isinstance(value, ColumnExpression):
            setattr(new, attr, _remap_asof(value, left, matched, right_names))
        elif isinstance(value, tuple) and any(
            isinstance(v, ColumnExpression) for v in value
        ):
            setattr(
                new,
                attr,
                tuple(
                    _remap_asof(v, left, matched, right_names)
                    if isinstance(v, ColumnExpression)
                    else v
                    for v in value
                ),
            )
    new._deps = tuple(
        _remap_asof(d, left, matched, right_names)
        if isinstance(d, ColumnExpression)
        else d
        for d in new._deps
    )
    return new


def asof_join(left, right, left_time, right_time, *on, how=JoinMode.LEFT, direction="backward", defaults=None, behavior=None):
    return AsofJoinResult(left, right, left_time, right_time, on, how, direction)


def asof_join_left(left, right, left_time, right_time, *on, **kw):
    return AsofJoinResult(left, right, left_time, right_time, on, JoinMode.LEFT, kw.get("direction", "backward"))


def asof_join_right(left, right, left_time, right_time, *on, **kw):
    return AsofJoinResult(right, left, right_time, left_time, on, JoinMode.LEFT, kw.get("direction", "backward"))


def asof_join_outer(left, right, left_time, right_time, *on, **kw):
    return AsofJoinResult(left, right, left_time, right_time, on, JoinMode.OUTER, kw.get("direction", "backward"))


def asof_now_join(left, right, *on, how=JoinMode.INNER, **kw):
    return left.asof_now_join(right, *on, how=how)


# ---------------------------------------------------------------------------
# window joins (reference: _window_join.py:1217)
# ---------------------------------------------------------------------------
class WindowJoinResult:
    def __init__(self, left, right, left_time, right_time, window, on, how):
        win = window
        if not isinstance(win, (TumblingWindow, SlidingWindow)):
            raise NotImplementedError("window_join supports tumbling/sliding windows")

        def assign(t):
            return [w[0] for w in win.assign(t)]

        lflat = left.with_columns(
            _pw_lw=ApplyExpression(assign, dt.ANY, args=(left_time,))
        ).flatten(this._pw_lw)
        rflat = right.with_columns(
            _pw_rw=ApplyExpression(assign, dt.ANY, args=(right_time,))
        ).flatten(this._pw_rw)
        conds = [lflat._pw_lw == rflat._pw_rw]
        for cond in on:
            conds.append(
                getattr(lflat, cond._left.name) == getattr(rflat, cond._right.name)
            )
        self._join = lflat.join(rflat, *conds, how=how)
        self._lflat, self._rflat = lflat, rflat
        self._left, self._right = left, right

    def select(self, *args, **kwargs) -> Table:
        exprs = {}
        for arg in args:
            exprs[arg.name] = arg
        exprs.update(kwargs)
        remapped = {
            name: _remap(e, {id(self._left): self._lflat, id(self._right): self._rflat})
            for name, e in exprs.items()
        }
        return self._join.select(**remapped)


def window_join(left, right, left_time, right_time, window, *on, how=JoinMode.INNER):
    return WindowJoinResult(left, right, left_time, right_time, window, on, how)


def window_join_inner(left, right, left_time, right_time, window, *on):
    return WindowJoinResult(left, right, left_time, right_time, window, on, JoinMode.INNER)


def window_join_left(left, right, left_time, right_time, window, *on):
    return WindowJoinResult(left, right, left_time, right_time, window, on, JoinMode.LEFT)


from . import time_utils  # noqa: E402
from .time_utils import inactivity_detection, utc_now  # noqa: E402
