"""Temporal behaviors: delay / cutoff / keep_results
(reference: python/pathway/stdlib/temporal/temporal_behavior.py:10-101).

Behaviors bound state and control emission cadence of windows.  They are
carried as metadata on windowed operations; the buffering/forgetting engine
operators (reference postpone_core/ignore_late,
src/engine/dataflow/operators/time_column.rs:380,677) consume them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

__all__ = [
    "Behavior",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "common_behavior",
    "exactly_once_behavior",
]


class Behavior:
    pass


@dataclass
class CommonBehavior(Behavior):
    delay: Optional[Any] = None
    cutoff: Optional[Any] = None
    keep_results: bool = True


def common_behavior(
    delay: Optional[Any] = None,
    cutoff: Optional[Any] = None,
    keep_results: bool = True,
) -> CommonBehavior:
    return CommonBehavior(delay, cutoff, keep_results)


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Optional[Any] = None


def exactly_once_behavior(shift: Optional[Any] = None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift)
