"""Session window reduction.

Sessions can't desugar to a static key (membership depends on neighbors), so
they reduce via a sorted-tuple accumulation per instance followed by a host
session-splitting pass — incremental at the granularity of the instance
(reference session window machinery: stdlib/temporal/_window.py SessionWindow).
"""

from __future__ import annotations

import math
from typing import Any

from ...internals import api_reducers as reducers
from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, ReducerExpression, smart_coerce
from ...internals.table import Table
from ...internals.thisclass import this


def _num(v: Any) -> float:
    import datetime

    if isinstance(v, datetime.timedelta):
        return v.total_seconds()
    if isinstance(v, datetime.datetime):
        return v.timestamp()
    return v


def reduce_session(windowed, *args, **kwargs) -> Table:
    win = windowed.window
    table = windowed.table
    key_expr = windowed.key_expr
    if win.max_gap is not None:
        gap = _num(win.max_gap)
        belong = lambda a, b: (_num(b) - _num(a)) <= gap
    elif win.predicate is not None:
        belong = win.predicate
    else:
        raise ValueError("session window needs max_gap or predicate")

    # pack (time, row_key) tuples per instance
    grouping = []
    if windowed.instance is not None:
        grouping.append(windowed.instance)
    aug = table.with_columns(_pw_t=key_expr)

    # Behaviors (NOTE: the reference silently IGNORES behaviors on session
    # windows — SessionWindow._apply takes `behavior` and never reads it,
    # /root/reference/python/pathway/stdlib/temporal/_window.py:111-146.
    # Here CommonBehavior is supported with row-time semantics: delay holds
    # a row until clock >= t+delay; cutoff drops rows arriving after clock
    # passed t+cutoff; keep_results=False additionally PRUNES rows past the
    # cutoff from the per-instance accumulation, which both bounds state and
    # retracts the frozen sessions via recompute.  keep_results=True keeps
    # every surviving row in the instance accumulation (results must stay
    # even if the instance later recomputes), so per-instance state is
    # bounded only by the cutoff-surviving row count.)
    gate = None
    cutoff_c = None
    keep_results = True
    behavior = windowed.behavior
    if behavior is not None:
        from .temporal_behavior import CommonBehavior, ExactlyOnceBehavior

        if isinstance(behavior, ExactlyOnceBehavior):
            raise NotImplementedError(
                "exactly-once is not defined for merging session windows; "
                "use common_behavior(delay, cutoff, keep_results)"
            )
        if not isinstance(behavior, CommonBehavior):
            raise TypeError(f"unsupported window behavior: {behavior!r}")
        release = expire = None
        if behavior.delay is not None:
            d = _num(behavior.delay)
            release = ApplyExpression(
                lambda t, d=d: _num(t) + d, dt.FLOAT, args=(this._pw_t,)
            )
        if behavior.cutoff is not None:
            cutoff_c = _num(behavior.cutoff)
            expire = ApplyExpression(
                lambda t, c=cutoff_c: _num(t) + c, dt.FLOAT, args=(this._pw_t,)
            )
        keep_results = behavior.keep_results
        if release is not None or expire is not None:
            aug, gate = aug._time_gate(this._pw_t, release, expire)
    grouped = aug.groupby(*[_rebind(g, table, aug) for g in grouping]) if grouping else aug.groupby()
    packed_cols = {}
    if grouping:
        for gi, g in enumerate(grouping):
            name = g.name if hasattr(g, "name") else f"_pw_instance_{gi}"
            packed_cols[name] = _rebind(g, table, aug)
    packed = grouped.reduce(
        **packed_cols,
        _pw_sessions=reducers.sorted_tuple(
            ApplyExpression(
                lambda t, *vals: (_num(t), vals),
                dt.ANY,
                args=(aug._pw_t, *[getattr(aug, c) for c in table.column_names]),
            )
        ),
    )

    def split_sessions(rows):
        sessions = []
        current = []
        prev_t = None
        for t, vals in rows:
            if prev_t is not None and not belong(prev_t, t):
                sessions.append(current)
                current = []
            current.append((t, vals))
            prev_t = t
        if current:
            sessions.append(current)
        return [
            ((s[0][0], s[-1][0]), tuple(s)) for s in sessions
        ]

    exploded = packed.with_columns(
        _pw_split=ApplyExpression(split_sessions, dt.ANY, args=(packed._pw_sessions,))
    ).flatten(this._pw_split)
    exploded = exploded.with_columns(
        _pw_window_start=ApplyExpression(lambda s: s[0][0], dt.FLOAT, args=(this._pw_split,)),
        _pw_window_end=ApplyExpression(lambda s: s[0][1], dt.FLOAT, args=(this._pw_split,)),
        _pw_rows=ApplyExpression(lambda s: s[1], dt.ANY, args=(this._pw_split,)),
    )
    # now evaluate requested reducers over the packed rows per session
    out_exprs = {}
    col_names = list(table.column_names)
    for arg in args:
        out_exprs[arg.name] = arg
    out_exprs.update(kwargs)

    final_exprs = {}
    for name, e in out_exprs.items():
        final_exprs[name] = _session_expr(e, exploded, col_names)
    out = exploded.select(**final_exprs)
    if gate is not None and cutoff_c is not None and not keep_results:
        gop = packed._engine_table.producer
        gate.sweep_hooks.append(_session_state_pruner(gop, cutoff_c))
    return out


def _session_state_pruner(gop, cutoff: float):
    """Sweep hook (keep_results=False): drop rows past the cutoff from the
    per-instance sorted-tuple accumulation and re-emit the packed rows — the
    downstream session split recomputes without them, retracting the frozen
    sessions AND keeping state bounded (the session analog of
    _groupby_sweeper's `del gop._groups[gk]`)."""
    si = next(
        i
        for i, spec in enumerate(gop.reducer_specs)
        if spec.out_name == "_pw_sessions"
    )

    def sweep(clock):
        touched = {}
        for gk, entry in list(gop._groups.items()):
            state = entry[2][si]
            expired = [
                h
                for h, (cnt, val) in state.items()
                if _num(val[0]) + cutoff <= clock
            ]
            if not expired:
                continue
            removed = 0
            for h in expired:
                cnt, _val = state[h]
                removed += cnt
                del state[h]
            entry[0] -= removed
            touched[gk] = None
        if not touched:
            return None
        delta = gop._emit(touched, list(gop.grouping_expressions.keys()))
        if delta is None:
            return None
        return (gop.output, delta)

    return sweep


def _rebind(expr, old_table, new_table):
    from ...internals.expression import ColumnReference

    if isinstance(expr, ColumnReference) and expr.table is old_table:
        return getattr(new_table, expr.name)
    return expr


def _session_expr(e, exploded, col_names):
    """Translate reducers/refs into host computations over the packed rows."""
    from ...internals.expression import ColumnReference

    if isinstance(e, ReducerExpression):
        reducer = e._reducer()
        arg_exprs = list(e._args)

        def agg(rows, _reducer=reducer, _arg_exprs=arg_exprs):
            state = _reducer.init_state()
            for i, (t, vals) in enumerate(rows):
                row_map = dict(zip(col_names, vals))
                if _reducer.n_args == 0:
                    value = None
                elif len(_arg_exprs) == 1:
                    value = _scalar_eval(_arg_exprs[0], row_map)
                else:
                    value = tuple(_scalar_eval(a, row_map) for a in _arg_exprs)
                if getattr(e, "_needs_key_order", False):
                    value = (value, i)
                state = _reducer.update(state, value, 1, i, 0)
            result = _reducer.result(state)
            post = getattr(e, "_post", None)
            return post(result) if post else result

        return ApplyExpression(agg, dt.ANY, args=(exploded._pw_rows,))
    if isinstance(e, ColumnReference):
        if e.name in ("_pw_window_start", "_pw_window_end", "_pw_window_location"):
            return getattr(exploded, e.name if e.name != "_pw_window_location" else "_pw_window_start")
        if e.name in col_names:
            # take the value from the first row of the session
            idx = col_names.index(e.name)
            return ApplyExpression(
                lambda rows, _i=idx: rows[0][1][_i], dt.ANY, args=(exploded._pw_rows,)
            )
        return getattr(exploded, e.name)
    return e


def _scalar_eval(expr, row_map):
    """Evaluate an expression for a single row given a name->value map."""
    import numpy as np

    from ...internals.expression import EvalContext

    columns = {}
    for (tid_name), v in (()):  # pragma: no cover
        pass
    # build a 1-row context: map every (table_id, name) the expr references
    ctx_cols = {}
    for ref in expr._column_refs():
        ctx_cols[(id(ref.table), ref.name)] = np.array([row_map.get(ref.name)], dtype=object)
    ctx = EvalContext(ctx_cols, np.zeros(1, dtype=np.uint64))
    return expr._eval(ctx)[0]
