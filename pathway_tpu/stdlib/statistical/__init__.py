"""pw.statistical (reference: python/pathway/stdlib/statistical/_interpolate.py)."""

from __future__ import annotations

import enum

from ...internals import api_reducers as reducers
from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["interpolate", "InterpolateMode"]


class InterpolateMode(enum.Enum):
    LINEAR = "linear"


def interpolate(
    table: Table, timestamp, *values, mode: InterpolateMode = InterpolateMode.LINEAR
) -> Table:
    """Linearly interpolate missing (None) values along the timestamp order."""
    names = [v.name for v in values]
    packed = table.groupby().reduce(
        _pw_rows=reducers.sorted_tuple(
            ApplyExpression(
                lambda t, *vals: (t, vals), dt.ANY, args=(timestamp, *values)
            )
        )
    )

    def interp(rows):
        n = len(rows)
        out_rows = []
        cols = list(zip(*[vals for _, vals in rows])) if rows else []
        times = [t for t, _ in rows]
        filled = []
        for ci in range(len(cols)):
            col = list(cols[ci])
            for i in range(n):
                if col[i] is None:
                    # find neighbors
                    lo = next((j for j in range(i - 1, -1, -1) if col[j] is not None), None)
                    hi = next((j for j in range(i + 1, n) if col[j] is not None), None)
                    if lo is not None and hi is not None:
                        t0, t1 = times[lo], times[hi]
                        w = (times[i] - t0) / (t1 - t0) if t1 != t0 else 0.0
                        col[i] = col[lo] + (col[hi] - col[lo]) * w
                    elif lo is not None:
                        col[i] = col[lo]
                    elif hi is not None:
                        col[i] = col[hi]
            filled.append(col)
        for i in range(n):
            out_rows.append((times[i], tuple(c[i] for c in filled)))
        return out_rows

    exploded = packed.select(
        _pw_interp=ApplyExpression(interp, dt.ANY, args=(packed._pw_rows,))
    ).flatten(this._pw_interp)
    return exploded.select(
        timestamp=ApplyExpression(lambda r: r[0], dt.ANY, args=(this._pw_interp,)),
        **{
            name: ApplyExpression(
                lambda r, _i=i: r[1][_i], dt.ANY, args=(this._pw_interp,)
            )
            for i, name in enumerate(names)
        },
    )
