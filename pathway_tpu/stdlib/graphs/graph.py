"""Graph / WeightedGraph containers with cluster contraction
(reference: python/pathway/stdlib/graphs/graph.py:13-150 — _contract,
_contract_weighted, Graph.contracted_to_*, without_self_loops).

A clustering is a table keyed by vertex with a ``c`` column (the cluster the
vertex belongs to, itself a pointer).  Contraction relabels edge endpoints by
their clusters and merges parallel edges (summing weights for weighted
graphs).
"""

from __future__ import annotations

from dataclasses import dataclass

from ...internals import api_reducers as reducers
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["Graph", "WeightedGraph"]


@dataclass
class Graph:
    """A directed (multi)graph as a vertex table + edge table (u, v pointers)."""

    V: Table
    E: Table

    def without_self_loops(self) -> "Graph":
        return Graph(self.V, self.E.filter(this.u != this.v))

    def _relabeled_edges(self, clustering: Table) -> Table:
        """Edge endpoints replaced by their clusters."""
        return self.E.select(
            u=clustering.ix(self.E.u).c,
            v=clustering.ix(self.E.v).c,
        )

    def contracted_to_multi_graph(self, clustering: Table) -> "Graph":
        edges = self._relabeled_edges(clustering)
        vertices = clustering.groupby(id=this.c).reduce(cnt=reducers.count())
        return Graph(vertices, edges)

    def contracted_to_unweighted_simple_graph(self, clustering: Table) -> "Graph":
        edges = self._relabeled_edges(clustering)
        simple = edges.groupby(id=edges.pointer_from(this.u, this.v)).reduce(
            u=reducers.any(this.u), v=reducers.any(this.v)
        )
        vertices = clustering.groupby(id=this.c).reduce(cnt=reducers.count())
        return Graph(vertices, simple)

    def contracted_to_weighted_simple_graph(
        self, clustering: Table
    ) -> "WeightedGraph":
        """Parallel edges merge; each original edge contributes weight 1."""
        edges = self._relabeled_edges(clustering)
        weighted = edges.groupby(id=edges.pointer_from(this.u, this.v)).reduce(
            u=reducers.any(this.u),
            v=reducers.any(this.v),
            weight=reducers.count(),
        )
        vertices = clustering.groupby(id=this.c).reduce(cnt=reducers.count())
        return WeightedGraph(vertices, weighted)


@dataclass
class WeightedGraph(Graph):
    """Graph whose edges carry a ``weight`` column."""

    @staticmethod
    def from_vertices_and_weighted_edges(V: Table, WE: Table) -> "WeightedGraph":
        return WeightedGraph(V, WE)

    def without_self_loops(self) -> "WeightedGraph":
        return WeightedGraph(self.V, self.E.filter(this.u != this.v))

    def contracted_to_weighted_simple_graph(
        self, clustering: Table
    ) -> "WeightedGraph":
        edges = self.E.select(
            u=clustering.ix(self.E.u).c,
            v=clustering.ix(self.E.v).c,
            weight=this.weight,
        )
        merged = edges.groupby(id=edges.pointer_from(this.u, this.v)).reduce(
            u=reducers.any(this.u),
            v=reducers.any(this.v),
            weight=reducers.sum(this.weight),
        )
        vertices = clustering.groupby(id=this.c).reduce(cnt=reducers.count())
        return WeightedGraph(vertices, merged)
