"""Shared graph schemas
(reference: python/pathway/stdlib/graphs/common.py:10-38 — Vertex, Edge,
Weight, Cluster, Clustering).

Vertices are rows of a vertex table; edges carry ``u``/``v`` pointer columns
(row keys of the vertex table, i.e. ``table.pointer_from(...)`` values).
"""

from __future__ import annotations

from ...internals.keys import Pointer
from ...internals.schema import Schema

__all__ = ["Vertex", "Edge", "Weight", "Cluster", "Clustering"]


class Vertex(Schema):
    pass


class Edge(Schema):
    u: Pointer
    v: Pointer


class Weight(Schema):
    weight: float


class Cluster(Schema):
    pass


class Clustering(Schema):
    c: Pointer
