"""Bellman–Ford single-source shortest paths, iterated to fixed point
(reference: python/pathway/stdlib/graphs/bellman_ford/impl.py:26-51 —
edge relaxation inside ``pw.iterate``).

``vertices`` must have a bool ``is_source`` column; ``edges`` carry
``u``/``v`` vertex pointers and a float ``dist`` column.  Returns a table
keyed like ``vertices`` with ``dist_from_source`` (``inf`` if unreachable).
"""

from __future__ import annotations

import math

from ...internals import api_reducers as reducers
from ...internals.expression import ApplyExpression, IfElseExpression
from ...internals.iterate import iterate
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["bellman_ford"]


def _relax(vertices_dist: Table, base: Table, edges: Table) -> Table:
    relaxed = edges.select(
        v=this.v,
        cand=vertices_dist.ix(edges.u).dist_from_source + edges.dist,
    )
    best = relaxed.groupby(id=this.v).reduce(cand=reducers.min(this.cand))
    joined = base.join_left(best, base.id == best.id)
    return joined.select(
        dist_from_source=ApplyExpression(
            lambda b, c: b if c is None or b <= c else c,
            None,
            args=(base.dist_from_source, best.cand),
        )
    )


def bellman_ford(vertices: Table, edges: Table) -> Table:
    initial = vertices.select(
        dist_from_source=IfElseExpression(this.is_source, 0.0, math.inf)
    )
    return iterate(
        lambda vertices_dist, base, edges: _relax(vertices_dist, base, edges),
        vertices_dist=initial,
        base=initial,
        edges=edges,
    )
