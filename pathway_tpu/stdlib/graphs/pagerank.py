"""PageRank over a live edge table
(reference: python/pathway/stdlib/graphs/pagerank/impl.py:18-41 — integer
power iteration unrolled ``steps`` times; this build uses float ranks with
the standard damping formulation, unrolled the same way so each step is an
incremental groupby/join that updates live as edges change).
"""

from __future__ import annotations

from ...internals import api_reducers as reducers
from ...internals.expression import ApplyExpression
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["pagerank"]


def pagerank(edges: Table, steps: int = 5, damping: float = 0.85) -> Table:
    """Rank every vertex that appears in ``edges`` (as u or v).

    Returns a table keyed by vertex pointer with a ``rank`` column.
    Dangling vertices (no outgoing edges) leak rank, as in the reference.
    """
    endpoints = edges.select(k=this.u).concat_reindex(edges.select(k=this.v))
    vertices = endpoints.groupby(id=this.k).reduce(cnt=reducers.count())

    out_deg = edges.groupby(id=this.u).reduce(degree=reducers.count())
    joined = vertices.join_left(out_deg, vertices.id == out_deg.id)
    degrees = joined.select(
        degree=ApplyExpression(
            lambda d: int(d) if d is not None else 0,
            None,
            args=(out_deg.degree,),
        )
    )

    ranks = vertices.select(rank=1.0)
    base = 1.0 - damping
    for _ in range(steps):
        contrib = edges.select(
            v=this.v,
            flow=damping
            * ranks.ix(edges.u).rank
            / ApplyExpression(
                lambda d: float(d) if d else 1.0,
                None,
                args=(degrees.ix(edges.u).degree,),
            ),
        )
        inflow = contrib.groupby(id=this.v).reduce(flow=reducers.sum(this.flow))
        rejoined = vertices.join_left(inflow, vertices.id == inflow.id)
        ranks = rejoined.select(
            rank=ApplyExpression(
                lambda f, b=base: b + (float(f) if f is not None else 0.0),
                None,
                args=(inflow.flow,),
            )
        )
    return ranks
