"""pw.graphs (reference: python/pathway/stdlib/graphs/ — louvain communities,
bellman-ford, pagerank).  Graph algorithms over edge tables; iterative
algorithms land together with pw.iterate."""

from __future__ import annotations

from dataclasses import dataclass

from ...internals import api_reducers as reducers
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["Graph", "degrees", "in_degrees", "out_degrees"]


@dataclass
class Graph:
    """A graph as vertex + edge tables (edges: u, v columns of pointers)."""

    V: Table
    E: Table


def out_degrees(edges: Table) -> Table:
    return edges.groupby(edges.u).reduce(u=this.u, degree=reducers.count())


def in_degrees(edges: Table) -> Table:
    return edges.groupby(edges.v).reduce(v=this.v, degree=reducers.count())


def degrees(edges: Table) -> Table:
    sym = edges.select(a=this.u).concat_reindex(edges.select(a=this.v))
    return sym.groupby(sym.a).reduce(a=this.a, degree=reducers.count())
