"""pw.graphs (reference: python/pathway/stdlib/graphs/ — louvain communities,
bellman-ford, pagerank, Graph/WeightedGraph contraction)."""

from __future__ import annotations

from ...internals import api_reducers as reducers
from ...internals.table import Table
from ...internals.thisclass import this
from . import bellman_ford as bellman_ford_mod
from . import louvain_communities
from . import pagerank as pagerank_mod
from .bellman_ford import bellman_ford
from .common import Cluster, Clustering, Edge, Vertex, Weight
from .graph import Graph, WeightedGraph
from .pagerank import pagerank

__all__ = [
    "Graph",
    "WeightedGraph",
    "Vertex",
    "Edge",
    "Weight",
    "Cluster",
    "Clustering",
    "bellman_ford",
    "pagerank",
    "louvain_communities",
    "degrees",
    "in_degrees",
    "out_degrees",
]


def out_degrees(edges: Table) -> Table:
    return edges.groupby(edges.u).reduce(u=this.u, degree=reducers.count())


def in_degrees(edges: Table) -> Table:
    return edges.groupby(edges.v).reduce(v=this.v, degree=reducers.count())


def degrees(edges: Table) -> Table:
    sym = edges.select(a=this.u).concat_reindex(edges.select(a=this.v))
    return sym.groupby(sym.a).reduce(a=this.a, degree=reducers.count())
