"""Louvain community detection
(reference: python/pathway/stdlib/graphs/louvain_communities/impl.py —
_propose_clusters/_one_step local moves, _louvain_level_fixed_iterations,
louvain_communities_fixed_iterations multi-level driver, exact_modularity).

The reference randomizes local moves and relies on ``gradual_broadcast`` of
an approximate total weight; this build is deterministic: every iteration
each vertex evaluates the standard modularity gain of joining each
neighbouring cluster,

    gain(i, C) = k_{i,C} - k_i * tot_C / (2m)

(with ``tot_C`` excluding ``k_i`` when i ∈ C), and adopts the argmax when it
strictly beats staying put (ties broken by cluster key, so runs are
reproducible).  Iterations are dataflow rounds — join + groupby + argmax —
so clusterings refresh incrementally as edges change.  The global ``2m``
scalar reaches row contexts through a constant-key ix into the single-row
total table (the engine analog of the reference's gradual broadcast).

Works on a ``WeightedGraph`` whose edges are undirected (each edge stored
once; both endpoints count it).
"""

from __future__ import annotations

import numpy as np

from ...internals import api_reducers as reducers
from ...internals.expression import ApplyExpression
from ...internals.table import Table
from ...internals.thisclass import this
from .graph import WeightedGraph

__all__ = [
    "louvain_level_fixed_iterations",
    "louvain_communities_fixed_iterations",
    "exact_modularity",
]


def _initial_clustering(G: WeightedGraph) -> Table:
    """Every vertex in its own cluster (cluster id = vertex key)."""
    return G.V.select(c=this.id)


def _symmetric_edges(E: Table) -> Table:
    """Each undirected edge seen from both endpoints."""
    fwd = E.select(a=this.u, b=this.v, weight=this.weight)
    bwd = E.select(a=this.v, b=this.u, weight=this.weight)
    return fwd.concat_reindex(bwd)


def _one_iteration(clustering: Table, sym: Table, phase: int = 0) -> Table:
    # weighted degree k_i per vertex
    deg = sym.groupby(id=this.a).reduce(k=reducers.sum(this.weight))
    # single-row global: 2m = total symmetric weight (group key 0)
    total = sym.reduce(two_m=reducers.sum(this.weight))

    # candidate moves: vertex a -> cluster of a neighbour, k_{a,C} summed
    labelled = sym.select(
        a=this.a,
        c_b=clustering.ix(sym.b).c,
        weight=this.weight,
    )
    k_ic = labelled.groupby(id=labelled.pointer_from(this.a, this.c_b)).reduce(
        a=reducers.any(this.a),
        cand=reducers.any(this.c_b),
        w=reducers.sum(this.weight),
    )
    # tot_C = sum of member degrees per cluster
    member_k = clustering.select(
        c=this.c, k=deg.ix(clustering.id, context=clustering).k
    )
    tot = member_k.groupby(id=this.c).reduce(tot=reducers.sum(this.k))

    # reduce() with no grouping keys its single row at 0; evaluating to a
    # uint64 array makes the join use the values as keys directly
    zero_key = ApplyExpression(
        lambda a: np.zeros(len(a), dtype=np.uint64),
        None,
        args=(k_ic.a,),
        batched=True,
    )
    cand = k_ic.select(
        a=this.a,
        cand=this.cand,
        w=this.w,
        k_a=deg.ix(k_ic.a).k,
        own=clustering.ix(k_ic.a).c,
        tot_cand=tot.ix(k_ic.cand).tot,
        two_m=total.ix(zero_key).two_m,
    )

    def gain(w, k_a, own, cand_c, tot_cand, two_m):
        tot_adj = tot_cand - (k_a if own == cand_c else 0.0)
        return float(w) - float(k_a) * float(tot_adj) / float(two_m)

    scored = cand.select(
        a=this.a,
        cand=this.cand,
        own=this.own,
        score=ApplyExpression(
            gain,
            None,
            args=(this.w, this.k_a, this.own, this.cand, this.tot_cand, this.two_m),
        ),
    )
    # best candidate per vertex; deterministic tie-break on cluster key
    best = scored.groupby(id=this.a).reduce(
        choice=reducers.argmax(
            ApplyExpression(
                lambda s, c: (s, -int(c)), None, args=(this.score, this.cand)
            ),
            # payload keeps the cluster label pointer-typed (np.uint64) — a
            # python int would hash/serialize differently and split groups
            ApplyExpression(
                lambda c, s: (np.uint64(c), s), None, args=(this.cand, this.score)
            ),
        ),
    )
    own_score = (
        scored.filter(this.cand == this.own)
        .groupby(id=this.a)
        .reduce(stay=reducers.max(this.score))
    )

    sel = clustering.join_left(best, clustering.id == best.id).select(
        c=this.c, choice=best.choice
    )
    final = sel.join_left(own_score, sel.id == own_score.id)

    def pick(key, own_c, choice, stay, _phase=phase):
        # alternating-parity gate: only half the vertices move per iteration
        # (deterministic stand-in for the reference's randomized local moves —
        # simultaneous symmetric moves would swap labels forever)
        if (int(key) & 1) != (_phase & 1):
            return own_c
        if choice is None:
            return own_c
        cand_c, score = choice
        baseline = stay if stay is not None else 0.0
        if score > baseline + 1e-12 and cand_c != own_c:
            return np.uint64(cand_c)
        return own_c

    from ...internals.expression import IdExpression

    return final.select(
        c=ApplyExpression(
            pick, None, args=(IdExpression(sel), sel.c, sel.choice, own_score.stay)
        )
    )


def louvain_level_fixed_iterations(
    G: WeightedGraph, number_of_iterations: int = 5
) -> Table:
    """One Louvain level: repeated deterministic local moves
    (reference: _louvain_level_fixed_iterations, impl.py:252)."""
    clustering = _initial_clustering(G)
    sym = _symmetric_edges(G.E)
    for i in range(number_of_iterations):
        clustering = _one_iteration(clustering, sym, phase=i)
    return clustering


def louvain_communities_fixed_iterations(
    G: WeightedGraph, levels: int = 1, iterations_per_level: int = 5
) -> Table:
    """Multi-level Louvain: cluster, contract, repeat
    (reference: louvain_communities_fixed_iterations, impl.py:282-338).

    Returns a clustering of the ORIGINAL vertices (cluster labels from the
    final level, composed through the contractions)."""
    clustering = louvain_level_fixed_iterations(G, iterations_per_level)
    for _ in range(levels - 1):
        G = G.contracted_to_weighted_simple_graph(clustering)
        next_clustering = louvain_level_fixed_iterations(G, iterations_per_level)
        # compose: original vertex -> old cluster -> new cluster
        clustering = clustering.select(c=next_clustering.ix(clustering.c).c)
    return clustering


def exact_modularity(G: WeightedGraph, clustering: Table) -> float:
    """Q = Σ_C [ Σ_in(C)/(2m) − (Σ_tot(C)/(2m))² ]
    (reference: exact_modularity, impl.py:340-378).  Runs the graph and
    returns a float (host-side; for tests and evaluation)."""
    from ...internals.run import run as pw_run

    sym = _symmetric_edges(G.E)
    deg = sym.groupby(id=this.a).reduce(k=reducers.sum(this.weight))
    labelled = sym.select(
        c_a=clustering.ix(sym.a).c,
        c_b=clustering.ix(sym.b).c,
        weight=this.weight,
    )
    internal = (
        labelled.filter(this.c_a == this.c_b)
        .groupby(id=this.c_a)
        .reduce(w_in=reducers.sum(this.weight))
    )
    member_k = clustering.select(
        c=this.c, k=deg.ix(clustering.id, context=clustering).k
    )
    tot = member_k.groupby(id=this.c).reduce(tot=reducers.sum(this.k))
    pw_run(monitoring_level=None)

    keys_t, cols_t = tot._materialize()
    keys_i, cols_i = internal._materialize()
    _, sym_cols = sym._materialize()
    two_m = float(sym_cols["weight"].sum()) if len(sym_cols["weight"]) else 0.0
    if two_m == 0.0:
        return 0.0
    internal_by_key = dict(zip(keys_i.tolist(), cols_i["w_in"].tolist()))
    q = 0.0
    for key, tot_c in zip(keys_t.tolist(), cols_t["tot"].tolist()):
        w_in = internal_by_key.get(key, 0.0)
        q += w_in / two_m - (tot_c / two_m) ** 2
    return q
