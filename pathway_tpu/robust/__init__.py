"""Serve-path fault tolerance: deadlines, retries, circuit breakers,
the degradation ladder, and deterministic fault injection.

PR 1 made the retrieve→rerank serve fast (2 dispatches + 2 fetches),
PR 2 made it statically checked, PR 3 made it observable; this package
makes it *survivable*.  Individual device dispatches, peers, uploads,
and maintenance passes WILL fail under production traffic — the serve
surface must degrade instead of dying:

- ``Deadline`` / ``DeadlineExceeded`` (``deadline.py``): a wall-clock
  budget carried explicitly through serving → retrieve_rerank → model
  submit/fetch, with per-stage sub-budgets;
- ``retry_call`` + ``CircuitBreaker`` (``retry.py``): bounded,
  seeded-jitter retries for transient failures; per-model breakers
  that fail fast (and feed the ladder) when a model is persistently
  down;
- the degradation ladder (``degrade.py``): ``ServeResult`` response
  flags + ``pathway_serve_degraded_total{reason=...}`` counters for
  every rung — rerank_skipped / late_interaction_skipped /
  tail_skipped / shard_skipped / extractive_answer / retrieval_failed;
- deterministic fault injection (``inject.py``): named sites
  (``ivf.dispatch``, ``cross_encoder.fetch``, ``exchange.send``,
  ``ivf.absorb``, …) armable to raise / delay / hang via
  ``PATHWAY_FAULTS`` or a context manager, seeded and thread-safe —
  the chaos suite (tests/test_robust.py) proves every rung with it.

Nothing in this package touches jax or holds a lock across blocking
work; the hot-path static analyzer (pathway_tpu/analysis) understands
``retry_call(site, jitted_fn, ...)`` as a device dispatch so wrapped
launches keep their lock-discipline and budget accounting.
"""

from .deadline import Deadline, DeadlineExceeded, stage1_fraction
from .degrade import (
    EXTRACTIVE_ANSWER,
    HOST_FAILOVER,
    LATE_INTERACTION_SKIPPED,
    LOAD_SHED,
    PARTITION_LOST,
    REPLICA_LOST,
    RERANK_SKIPPED,
    RETRIEVAL_FAILED,
    SHARD_SKIPPED,
    TAIL_SKIPPED,
    ServeResult,
    extractive_answer,
    record_degraded,
)
from .inject import FaultInjected
from .retry import (
    CircuitBreaker,
    CircuitOpen,
    RetryPolicy,
    breaker,
    log_once,
    retry_call,
)
from . import inject

__all__ = [
    "CircuitBreaker",
    "CircuitOpen",
    "Deadline",
    "DeadlineExceeded",
    "EXTRACTIVE_ANSWER",
    "FaultInjected",
    "HOST_FAILOVER",
    "LATE_INTERACTION_SKIPPED",
    "LOAD_SHED",
    "PARTITION_LOST",
    "REPLICA_LOST",
    "RERANK_SKIPPED",
    "RETRIEVAL_FAILED",
    "RetryPolicy",
    "SHARD_SKIPPED",
    "ServeResult",
    "TAIL_SKIPPED",
    "breaker",
    "extractive_answer",
    "inject",
    "log_once",
    "record_degraded",
    "retry_call",
    "stage1_fraction",
]
