"""The degradation ladder: what a serve returns when a stage is down.

The north star is a serving tier that stays up for millions of users
while individual pieces fail (ROADMAP; PAPERS.md's multi-stage ranking
architectures all assume the retrieval tier outlives the rerank tier).
Each rung trades quality for availability, never silently — every
degraded serve is flagged on the response AND counted on the metrics
surface (``pathway_serve_degraded_total{reason=...}``):

=====================  ==========================  ==========================
failure                rung served                 response flag
=====================  ==========================  ==========================
reranker down /        stage-1 (retrieval) scores  ``rerank_skipped``
circuit open /
deadline tight
forward-index gather   previous-stage scores       ``late_interaction_skipped``
down / generation
mismatch / deadline
tight
exact tail             resident-only IVF search    ``tail_skipped``
unavailable
index shard down /     the live shards' merged     ``shard_skipped``
breaker open           candidates (recall lost on
                       the dead shard's partition
                       only)
generator down         extractive answer from      ``extractive_answer``
                       the top passages
SLO burn firing +      empty result set (shed at   ``load_shed``
shed-class priority    admission, never queued)
stage 1 down           empty result set            ``retrieval_failed``
fabric host dead /     a surviving host's rows     ``host_failover``
slow (re-routed)       (re-routed or hedged)
no healthy fabric      empty result set (the       ``replica_lost``
host remains           fleet, not the request,
                       is the outage)
index partition dead   the surviving partitions'   ``partition_lost``
/ slow in a            merged candidates (recall
partitioned fleet      lost on the dead
                       partition's keys only)
=====================  ==========================  ==========================

``ServeResult`` is a ``list`` subclass, so every existing caller that
iterates/compares rows keeps working; the ladder metadata rides on
``.degraded`` (tuple of rung flags) and ``.meta`` (e.g. the
``missing_docs`` ids whose text was evicted between retrieval and
rerank).  Stacked degradation is first-class: several rungs can fire in
ONE serve (e.g. ``tail_skipped`` + ``late_interaction_skipped``) —
``degraded`` carries each flag exactly once and ``meta`` mirrors the
full reason list under ``degraded_reasons`` so response consumers that
only look at metadata see every rung too.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import observe
from ..observe import trace as _trace

__all__ = [
    "EXTRACTIVE_ANSWER",
    "HOST_FAILOVER",
    "LATE_INTERACTION_SKIPPED",
    "LOAD_SHED",
    "PARTITION_LOST",
    "REPLICA_LOST",
    "RERANK_SKIPPED",
    "RETRIEVAL_FAILED",
    "SHARD_SKIPPED",
    "TAIL_SKIPPED",
    "ServeResult",
    "extractive_answer",
    "record_degraded",
]

RERANK_SKIPPED = "rerank_skipped"
LATE_INTERACTION_SKIPPED = "late_interaction_skipped"
TAIL_SKIPPED = "tail_skipped"
SHARD_SKIPPED = "shard_skipped"
EXTRACTIVE_ANSWER = "extractive_answer"
LOAD_SHED = "load_shed"
RETRIEVAL_FAILED = "retrieval_failed"
# serve-fabric rungs (serve/fabric.py): a request re-routed off a dead
# or slow host keeps a surviving host's full rows (host_failover); only
# when NO healthy host remains does it degrade to an empty flagged
# result (replica_lost) — a dead host is its shards' recall plus a
# flag, never an exception out of a serve call
HOST_FAILOVER = "host_failover"
REPLICA_LOST = "replica_lost"
# partitioned-fleet rung (serve/fabric.py scatter-gather): when the
# index is PARTITIONED across hosts a dead/slow host is not a replica
# to re-route around — its partition's candidates are simply absent.
# The serve keeps every surviving partition's merged rows and flags
# which partitions it lost; degraded results are never cached (a later
# clean serve must be able to recover the full recall)
PARTITION_LOST = "partition_lost"

# pre-resolved per-reason counters (reasons are the small fixed rung set)
_degraded_counters: Dict[str, observe.Counter] = {}


def record_degraded(reason: str, n: int = 1) -> None:
    """Count ``n`` degraded serves for ``reason`` on the existing
    /metrics surface (``pathway_serve_degraded_total{reason=...}``),
    and stamp the rung onto the active trace (observe/trace.py) — a
    recorded rung is exactly what the tail sampler's "always keep
    degraded serves" rule keys on."""
    c = _degraded_counters.get(reason)
    if c is None:
        c = _degraded_counters[reason] = observe.counter(
            "pathway_serve_degraded_total", reason=reason
        )
    c.inc(n)
    t = _trace.current()
    if t is not None:
        t.set_status(reason)


class ServeResult(list):
    """Serve rows plus ladder metadata.  Compares equal to a plain list
    of the same rows (existing tests and callers keep working); carries
    ``degraded`` — the tuple of rung flags that applied to this serve —
    and ``meta`` (e.g. ``missing_docs``).

    Stacked-degradation contract: ``degraded`` is DEDUPED (a rung that
    fired twice on one serve — e.g. a flag copied from stage 1 and
    re-applied by a stage fallback — appears once), and the full reason
    list is mirrored into ``meta["degraded_reasons"]`` so metadata-only
    consumers (response headers, the QA layer) report every rung."""

    __slots__ = ("degraded", "meta")

    def __init__(
        self,
        rows: Iterable[Any] = (),
        degraded: Sequence[str] = (),
        meta: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(rows)
        deduped: List[str] = []
        for flag in degraded:
            if flag not in deduped:
                deduped.append(flag)
        self.degraded = tuple(deduped)
        self.meta = dict(meta or {})
        if self.degraded and "degraded_reasons" not in self.meta:
            self.meta["degraded_reasons"] = list(self.degraded)

    @property
    def ok(self) -> bool:
        return not self.degraded

    def with_flags(
        self,
        degraded: Sequence[str] = (),
        meta: Optional[Dict[str, Any]] = None,
    ) -> "ServeResult":
        """A copy with extra flags/meta merged in (dedup, order kept)."""
        merged = list(self.degraded)
        for flag in degraded:
            if flag not in merged:
                merged.append(flag)
        out_meta = dict(self.meta)
        out_meta.update(meta or {})
        # regenerated by __init__ from the MERGED flags — carrying the
        # old list over would under-report the new rungs
        out_meta.pop("degraded_reasons", None)
        return ServeResult(self, degraded=merged, meta=out_meta)


def _sentences(text: str) -> List[str]:
    out: List[str] = []
    cur: List[str] = []
    for ch in str(text):
        cur.append(ch)
        if ch in ".!?":
            s = "".join(cur).strip()
            if s:
                out.append(s)
            cur = []
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def extractive_answer(
    question: str, docs: Sequence[str], max_sentences: int = 2
) -> str:
    """Generator-down rung: a cheap extractive answer — the sentences
    from the top passages sharing the most terms with the question
    (ranked by overlap, ties broken by passage rank so the retriever's
    ordering still matters).  Not an LLM answer; an honest degraded one
    that keeps the QA surface returning *grounded* text instead of 500s."""
    q_terms = {t for t in str(question).lower().split() if len(t) > 2}
    scored: List[Tuple[float, int, str]] = []
    for rank, doc in enumerate(docs):
        for sent in _sentences(doc):
            terms = set(sent.lower().split())
            overlap = len(q_terms & terms)
            if overlap:
                scored.append((-(overlap / (1 + len(terms) ** 0.5)), rank, sent))
    scored.sort()
    picked = [s for _, _, s in scored[:max_sentences]]
    if not picked:
        # nothing overlaps: fall back to the leading sentence of the
        # top passage — still grounded in the retrieved context
        for doc in docs:
            lead = _sentences(doc)[:1]
            if lead:
                picked = lead
                break
    return " ".join(picked)
