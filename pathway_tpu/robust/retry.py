"""Retry with exponential backoff + deterministic jitter, and per-model
circuit breakers.

Transient failures — a device dispatch rejected by a full queue, a
socket hiccup mid-exchange, a tail-matrix upload racing a device OOM —
must not surface to a serve caller when simply trying again would
succeed.  ``retry_call`` wraps one named *site* (the same name the
fault-injection registry uses — ``robust/inject.py`` fires before every
attempt, so every retry site is automatically chaos-testable) with a
bounded attempt budget and exponential backoff whose jitter is seeded
per ``(site, attempt)``: a failure soak replays identically.

Persistent failures must stop being retried before they melt the serve
path: a ``CircuitBreaker`` per model opens after N *consecutive*
failures (every call then fails fast with ``CircuitOpen``, which the
degradation ladder turns into a flagged stage-skip — see
``ops/retrieve_rerank.py``), and half-opens after a cool-down to let
ONE probe through; a probe success closes it, a probe failure re-opens
it and restarts the timer.

Everything here is host-side integer/float work — no jax, no locks held
across anything blocking — so the analyzer's lock-discipline and
hidden-sync rules see nothing to flag (ISSUE 4's "robust calls must be
lock-clean").
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Set, Tuple, Type

from .. import config, observe
from . import inject
from .deadline import Deadline, DeadlineExceeded

__all__ = [
    "CircuitBreaker",
    "CircuitOpen",
    "RetryPolicy",
    "breaker",
    "log_once",
    "retry_call",
]

_logger = logging.getLogger("pathway_tpu.robust")
_logged_keys: Set[str] = set()
_logged_lock = threading.Lock()


def log_once(key: str, msg: str, *args: Any) -> None:
    """Log ``msg`` at WARNING the FIRST time ``key`` is seen (per
    process).  Degradation paths swallow exceptions by design — this
    keeps the first instance of each failure mode visible in logs
    without letting a hot failing path flood them."""
    with _logged_lock:
        if key in _logged_keys:
            return
        _logged_keys.add(key)
    _logger.warning(msg, *args)


class CircuitOpen(RuntimeError):
    """Fail-fast: the named breaker is open (recent consecutive
    failures); callers degrade instead of queueing more doomed work."""

    def __init__(self, name: str):
        super().__init__(f"circuit breaker {name!r} is open")
        self.name = name


class RetryPolicy:
    """Attempt budget + backoff schedule for one retry site."""

    __slots__ = ("attempts", "base_delay_s", "max_delay_s", "seed")

    def __init__(
        self,
        attempts: int = 3,
        base_delay_s: float = 0.005,
        max_delay_s: float = 0.2,
        seed: int = 0,
    ):
        self.attempts = max(1, int(attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.seed = int(seed)

    def delay_s(self, site: str, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based): exponential with
        full deterministic jitter — ``Random((seed, site, attempt))``
        picks a point in [base/2, base*2^a], so concurrent failing
        sites de-synchronize yet every run replays identically."""
        cap = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        lo = min(self.base_delay_s * 0.5, cap)
        return lo + random.Random(
            f"{self.seed}:{site}:{attempt}"
        ).random() * max(0.0, cap - lo)

    @classmethod
    def from_env(cls, site: str) -> "RetryPolicy":
        """Global knobs ``robust.retry_{attempts,base_ms,max_ms,seed}``
        with per-site attempt overrides ``PATHWAY_RETRY_ATTEMPTS_<SITE>``
        (site upper-cased, dots → underscores — the registry's
        ``get_site`` resolution)."""
        return cls(
            attempts=config.get_site("robust.retry_attempts", site),
            base_delay_s=config.get("robust.retry_base_ms") * 1e-3,
            max_delay_s=config.get("robust.retry_max_ms") * 1e-3,
            seed=config.get("robust.retry_seed"),
        )


# cached per-site policies + observe counters (sites are a small fixed
# set of serve-path literals)
_policies: Dict[str, RetryPolicy] = {}
_retry_counters: Dict[str, observe.Counter] = {}
_exhausted_counters: Dict[str, observe.Counter] = {}


def _policy_for(site: str) -> RetryPolicy:
    p = _policies.get(site)
    if p is None:
        p = _policies[site] = RetryPolicy.from_env(site)
    return p


def _count_retry(site: str, exhausted: bool) -> None:
    store = _exhausted_counters if exhausted else _retry_counters
    c = store.get(site)
    if c is None:
        name = (
            "pathway_robust_retry_exhausted_total"
            if exhausted
            else "pathway_robust_retries_total"
        )
        c = store[site] = observe.counter(name, site=site)
    c.inc()


def retry_call(
    site: str,
    fn: Callable[..., Any],
    *args: Any,
    deadline: Optional[Deadline] = None,
    policy: Optional[RetryPolicy] = None,
    retryable: Tuple[Type[BaseException], ...] = (Exception,),
    breaker: Optional["CircuitBreaker"] = None,
    **kwargs: Any,
) -> Any:
    """Call ``fn(*args, **kwargs)`` with the site's retry budget.

    Per attempt: the breaker (if any) gates entry, the fault-injection
    site ``site`` fires (so chaos tests reach this exact code path),
    then ``fn`` runs.  ``DeadlineExceeded`` and ``CircuitOpen`` are
    never retried — they are policy outcomes, not transient failures.
    The backoff sleep is capped at the deadline's remaining budget and
    the final failure re-raises the last error."""
    pol = policy or _policy_for(site)
    last: Optional[BaseException] = None
    for attempt in range(pol.attempts):
        if deadline is not None:
            deadline.check(site)
        if breaker is not None and not breaker.allow():
            raise CircuitOpen(breaker.name)
        try:
            inject.fire(site, deadline=deadline)
            result = fn(*args, **kwargs)
        except (DeadlineExceeded, CircuitOpen):
            # policy outcomes, not model outcomes: a half-open probe
            # cancelled by its deadline proved nothing — release the
            # probe slot or the breaker wedges in fail-fast forever
            # (no caller could ever record an outcome again)
            if breaker is not None:
                breaker.abort_probe()
            raise
        except retryable as exc:
            if breaker is not None:
                breaker.record_failure()
            last = exc
            if attempt + 1 >= pol.attempts:
                break
            delay = pol.delay_s(site, attempt + 1)
            if deadline is not None:
                remaining = deadline.remaining_s()
                if remaining <= 0:
                    break  # budget spent: no retry happens, count none
                delay = min(delay, remaining)
            _count_retry(site, exhausted=False)
            time.sleep(delay)
            continue
        if breaker is not None:
            breaker.record_success()
        return result
    _count_retry(site, exhausted=True)
    assert last is not None
    raise last


class CircuitBreaker:
    """Consecutive-failure circuit breaker with timed half-open probes.

    closed → (``failure_threshold`` consecutive failures) → open →
    (``reset_s`` cool-down) → half-open, ONE probe allowed → success
    closes / failure re-opens.  ``allow()`` is the gate; callers report
    outcomes through ``record_success``/``record_failure`` (or let
    ``retry_call`` do it).  State is exported at scrape time as
    ``pathway_robust_breaker_open{breaker=...}`` via the flight-recorder
    provider registry — zero hot-path cost."""

    def __init__(
        self,
        name: str,
        failure_threshold: Optional[int] = None,
        reset_s: Optional[float] = None,
    ):
        self.name = name
        self.failure_threshold = int(
            failure_threshold
            if failure_threshold is not None
            else config.get("robust.breaker_threshold")
        )
        self.reset_s = float(
            reset_s
            if reset_s is not None
            else config.get("robust.breaker_reset_s")
        )
        self._lock = threading.Lock()
        self._failures = 0  # consecutive
        self._opened_at: Optional[float] = None
        self._probing = False
        self.stats = {"opens": 0, "fail_fast": 0}
        observe.register_provider(self)

    # -- state machine ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if time.monotonic() - self._opened_at >= self.reset_s:
            return "half_open"
        return "open"

    def allow(self) -> bool:
        """True when a call may proceed (closed, or the single
        half-open probe); False = fail fast."""
        with self._lock:
            state = self._state_locked()
            if state == "closed":
                return True
            if state == "half_open" and not self._probing:
                self._probing = True
                return True
            self.stats["fail_fast"] += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def abort_probe(self) -> None:
        """Release the half-open probe slot WITHOUT recording an outcome
        — for a probe call cancelled by policy (deadline) before the
        model could prove anything.  Harmless when no probe is held; in
        the rare race where another thread holds the probe this may
        admit one extra probe, which is benign (a wedged breaker is
        not)."""
        with self._lock:
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._probing or (
                self._opened_at is None
                and self._failures >= self.failure_threshold
            ):
                # probe failed, or the consecutive-failure budget spent:
                # (re)open and restart the cool-down clock
                self._opened_at = time.monotonic()
                self._probing = False
                self.stats["opens"] += 1

    def reset(self) -> None:
        self.record_success()

    # -- flight-recorder provider ------------------------------------------
    def observe_metrics(self):
        labels = {"breaker": self.name}
        state = self.state
        yield (
            "gauge",
            "pathway_robust_breaker_open",
            labels,
            {"closed": 0.0, "half_open": 0.5, "open": 1.0}[state],
        )
        yield (
            "counter",
            "pathway_robust_breaker_opens_total",
            labels,
            self.stats["opens"],
        )
        yield (
            "counter",
            "pathway_robust_breaker_fail_fast_total",
            labels,
            self.stats["fail_fast"],
        )


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker(name: str, **kwargs: Any) -> CircuitBreaker:
    """Process-wide breaker registry — one breaker per model/site name,
    shared by every pipeline that scores through that model."""
    with _breakers_lock:
        b = _breakers.get(name)
        if b is None:
            b = _breakers[name] = CircuitBreaker(name, **kwargs)
        return b
