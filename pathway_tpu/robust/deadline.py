"""Serve-call deadlines with per-stage sub-budgets.

The serving stack's latency contract is round-trip counts, not wall
clock — but a production serve call still needs a wall-clock *budget*:
a hung device dispatch, a wedged peer, or a pathological rerank batch
must bound how long the caller waits, and the multi-stage pipeline must
know how much of the budget each stage may spend ("Accelerating
Retrieval-Augmented Generation" budgets retrieval vs inference
explicitly; every SLO-bearing serving tier does).

A ``Deadline`` is an absolute point on the monotonic clock, created
from a budget and carried explicitly through ``serving.py`` →
``retrieve_rerank.py`` → model ``submit()``/fetch.  It is cheap (one
``time.monotonic`` read per check), immutable, and thread-safe by
construction.  ``sub_budget`` carves a stage budget out of the
remaining time without ever extending the parent — a stage can run out
early, never late.

Exceeding a deadline raises ``DeadlineExceeded`` *inside* the pipeline;
the pipeline's contract with the user is degrade-not-die: stage-1
results already on host are served (flagged ``rerank_skipped``) instead
of the exception propagating (ops/retrieve_rerank.py).
"""

from __future__ import annotations

import time
from typing import Optional

from .. import config

__all__ = ["Deadline", "DeadlineExceeded"]


class DeadlineExceeded(TimeoutError):
    """A serve stage ran past its deadline.  ``stage`` names the check
    site; the serving pipeline converts this into a degraded response
    rather than letting it reach the user."""

    def __init__(self, stage: str, overshoot_s: float = 0.0):
        super().__init__(
            f"deadline exceeded at {stage!r}"
            + (f" (by {overshoot_s * 1e3:.1f} ms)" if overshoot_s > 0 else "")
        )
        self.stage = stage
        self.overshoot_s = overshoot_s


class Deadline:
    """An absolute monotonic-clock deadline.

    ``Deadline(0.25)`` — a quarter second from now.  Immutable;
    share freely across threads.
    """

    __slots__ = ("_at",)

    def __init__(self, budget_s: float, *, _at: Optional[float] = None):
        self._at = _at if _at is not None else time.monotonic() + float(budget_s)

    # -- constructors -------------------------------------------------------
    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(float(ms) * 1e-3)

    @classmethod
    def from_env(cls) -> Optional["Deadline"]:
        """Per-serve default budget from ``serve.deadline_ms``;
        None (no deadline) when unset or <= 0."""
        ms = config.get("serve.deadline_ms")
        return cls.after_ms(ms) if ms > 0 else None

    # -- queries ------------------------------------------------------------
    def remaining_s(self) -> float:
        """Seconds left (negative once expired)."""
        return self._at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self._at

    def check(self, stage: str) -> None:
        """Raise ``DeadlineExceeded`` if the budget is spent."""
        over = time.monotonic() - self._at
        if over >= 0:
            raise DeadlineExceeded(stage, over)

    def sub_budget(self, fraction: float) -> "Deadline":
        """A stage deadline spending at most ``fraction`` of the time
        REMAINING now — never later than the parent (a stage may finish
        the serve early, it cannot extend it)."""
        remaining = self.remaining_s()
        if remaining <= 0:
            return Deadline(0.0, _at=self._at)
        child_at = time.monotonic() + remaining * max(0.0, min(1.0, fraction))
        return Deadline(0.0, _at=min(child_at, self._at))

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining_s() * 1e3:.1f}ms)"


def stage1_fraction() -> float:
    """Share of a serve budget granted to stage 1 (retrieval); stage 2
    runs on whatever remains of the parent budget.  Clamped to (0, 1]
    by the registry's declared bounds."""
    return config.get("serve.stage1_fraction")
