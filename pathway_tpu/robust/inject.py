"""Deterministic fault-injection registry for the serve stack.

Every failure-handling path in this repo (retry, circuit breaker,
degradation ladder — ``robust/retry.py``, ``robust/degrade.py``) must be
*provable* by a test, and real device/socket failures are neither
deterministic nor portable to CPU CI.  This registry gives each
instrumented failure point a NAME — ``ivf.dispatch``,
``cross_encoder.fetch``, ``exchange.send``, ``ivf.absorb``,
``forward.upload``, ``forward.gather``, ``forward.absorb``, the
sharded-serve family ``shard.dispatch`` / ``shard.merge`` /
``shard.absorb`` (each also addressable per shard as
``shard.<site>.<n>``, so a game-day can kill exactly one shard of a
group), the serve-cache pair ``cache.get`` / ``cache.put``
(pathway_tpu/cache — a faulted lookup degrades to a recompute MISS and
a faulted store drops the entry; the serve result is never wrong and
never fails, proven by the chaos triple in tests/test_robust.py), the
continuous-decode triple ``generator.prefill`` / ``generator.step`` /
``generator.slot_free`` (serve/decode.py — a prefill fault degrades
that request to an empty flagged result the QA ladder's
``extractive_answer`` rung absorbs, a persistent step fault resolves
every in-flight request with its tokens emitted so far, flagged, and a
slot-free fault QUARANTINES the slot; the step loop never stalls and no
other slot's K/V is touched — ``slot_free`` even fires under an
already-spent deadline so an armed hang releases immediately), the
speculative-decode pair ``generator.draft`` / ``generator.verify``
(serve/decode.py — a faulted draft or verify round falls back to the
plain non-speculative step chunk, TOKEN-IDENTICAL, counted on
``pathway_serve_degraded_total{reason="speculation_disabled"}``, with
a cooldown so a persistent fault never pays the retry ladder per
chunk; pure-ngram rounds fire ``generator.draft`` too, so a fault
disables all speculation uniformly), and
the tracing pair ``trace.record`` / ``trace.export``
(pathway_tpu/observe/trace.py — ANY armed fault in the tracing path,
raise/delay/hang alike, degrades to dropped spans counted on
``pathway_trace_spans_dropped_total`` and a flagged-empty ``/traces``
payload; the tracing layer fires these sites under an already-spent
deadline so even a hang releases immediately and a serve is never
failed or stalled by its own observability), and the live-ingest
triple ``ingest.poll`` / ``ingest.embed`` / ``ingest.commit``
(serve/ingest.py — a faulted poll RETRIES, its documents never leave
the queue; a faulted embed or commit DROPS only that batch's
documents, counted on ``pathway_ingest_failures_total{stage=...}``;
serve results stay clean and bit-identical because the index simply
does not advance, and every ingest site fires under an already-spent
deadline so an armed hang releases instantly — maintenance never
stalls), the serve-fabric triple ``fabric.route`` / ``fabric.send`` /
``fabric.recv`` (serve/fabric.py — a route fault falls back from the
affinity host to the least-loaded healthy one flagged
``host_failover``; a send/recv fault fails over to a surviving host
(breaker fed, same rung) and only an exhausted fleet degrades to an
empty ``replica_lost`` result — the request NEVER sees an exception),
the partitioned-fabric triple ``fabric.scatter`` / ``fabric.gather`` /
``partition.absorb`` (serve/fabric.py — a scatter fault loses THAT
partition only, flagged ``partition_lost`` with the survivors' merge
served; a gather fault stops the wait and serves whatever partitions
already resolved, the stragglers flagged; an absorb fault drops only
the routed batch, counted on
``pathway_partition_absorb_dropped_total`` and re-committable — every
site honors a spent deadline so an armed hang releases immediately),
the warm-state pair ``warmstate.snapshot`` / ``warmstate.restore``
(serve/warmstate.py — a faulted snapshot is a SKIPPED cadence counted
on ``pathway_warmstate_snapshot_skipped_total``, never a torn blob; a
faulted restore degrades bring-up to flagged cold ingest counted on
``pathway_warmstate_restore_failures_total{kind}``, never a wrong
index), the distributed control-plane pair ``dist.barrier`` /
``dist.broadcast`` (parallel/distributed.py — a faulted or timed-out
barrier/broadcast degrades to FLAGGED local-only agreement, counted on
``pathway_dist_degraded_total{site}``; a serve is never hung on the
coordination service), the S3 snapshot-backend triple ``s3.get`` /
``s3.put`` / ``s3.list`` (persistence/backends.py — transient object-
store errors retry with the standard seeded-jitter backoff through
``retry_call``), … — and lets a test (or
an operator running a game-day) arm any site to

- ``raise`` a ``FaultInjected`` (a transient dispatch/socket error),
- ``delay`` execution by a fixed duration (a slow link or device), or
- ``hang`` until the caller's deadline (or a bounded cap) expires,

either via the ``PATHWAY_FAULTS`` environment variable or the
``armed(...)`` context manager.  Triggering is seeded and thread-safe:
a probability ``p < 1`` draws from a per-site ``random.Random`` keyed
by ``(seed, site)``, so a 1%-failure soak replays identically.

The disarmed fast path is one module-global integer compare — serving
code calls ``fire(site)`` unconditionally and pays nothing in
production.  Sites are instrumented through ``robust.retry_call`` (which
fires its site before every attempt) plus explicit ``fire`` calls on
fetch/maintenance paths.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .. import observe
from .deadline import Deadline, DeadlineExceeded

__all__ = [
    "FaultInjected",
    "any_armed",
    "arm",
    "armed",
    "disarm",
    "fire",
    "fired_count",
    "load_env",
]

_MODES = ("raise", "delay", "hang")

# cached fired-counter per (site, mode): the label sets are tiny
_fired_counters: Dict[Tuple[str, str], observe.Counter] = {}


def _fired_counter(site: str, mode: str) -> observe.Counter:
    key = (site, mode)
    c = _fired_counters.get(key)
    if c is None:
        c = _fired_counters[key] = observe.counter(
            "pathway_robust_faults_fired_total", site=site, mode=mode
        )
    return c


class FaultInjected(RuntimeError):
    """The error an armed ``raise`` site throws — stands in for a
    transient device dispatch / socket / upload failure."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site!r}")
        self.site = site


class _Site:
    """One armed site (internal).  All mutation under the module lock;
    ``fire`` copies what it needs and sleeps OFF the lock."""

    __slots__ = (
        "site", "mode", "times", "p", "delay_s", "hang_s", "rng",
        "fired", "disarmed",
    )

    def __init__(
        self,
        site: str,
        mode: str,
        times: Optional[int],
        p: float,
        delay_s: float,
        hang_s: float,
        seed: int,
    ):
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r} (want {_MODES})")
        self.site = site
        self.mode = mode
        self.times = times  # None = unlimited
        self.p = float(p)
        self.delay_s = float(delay_s)
        self.hang_s = float(hang_s)
        self.rng = random.Random(f"{seed}:{site}")
        self.fired = 0
        self.disarmed = threading.Event()


_lock = threading.Lock()
_sites: Dict[str, _Site] = {}
_armed_count = 0  # fast-path guard: fire() is a no-op while this is 0
_env_loaded = False


def arm(
    site: str,
    mode: str = "raise",
    *,
    times: Optional[int] = None,
    p: float = 1.0,
    delay_s: float = 0.0,
    hang_s: float = 30.0,
    seed: int = 0,
) -> None:
    """Arm ``site``.  ``times`` bounds how often it triggers (None =
    every eligible call); ``p`` is the per-call trigger probability
    (seeded, deterministic); ``delay_s`` is the ``delay`` duration;
    ``hang_s`` caps a ``hang`` so an un-deadlined caller is released
    (as a ``FaultInjected``) instead of wedged forever."""
    global _armed_count
    spec = _Site(site, mode, times, p, delay_s, hang_s, seed)
    with _lock:
        old = _sites.get(site)
        if old is not None:
            old.disarmed.set()
        else:
            _armed_count += 1
        _sites[site] = spec


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site (or every site when None); releases hung calls."""
    global _armed_count
    with _lock:
        targets = [site] if site is not None else list(_sites)
        for name in targets:
            spec = _sites.pop(name, None)
            if spec is not None:
                spec.disarmed.set()
                _armed_count -= 1


@contextlib.contextmanager
def armed(site: str, mode: str = "raise", **kwargs: Any) -> Iterator[None]:
    """``with inject.armed("ivf.dispatch", "raise", times=1): ...`` —
    the test-suite front door; always disarms on exit."""
    arm(site, mode, **kwargs)
    try:
        yield
    finally:
        disarm(site)


def fired_count(site: str) -> int:
    with _lock:
        spec = _sites.get(site)
        return spec.fired if spec is not None else 0


def any_armed() -> bool:
    """True when at least one site is armed — the same fast-path guard
    ``fire`` uses, exposed so callers that need pre/post bookkeeping
    around a fire (the tracing layer's drop-on-any-fault contract) can
    skip it entirely in the unarmed steady state."""
    if not _env_loaded:
        load_env()
    return _armed_count != 0


def fire(site: str, deadline: Optional[Deadline] = None) -> None:
    """The instrumentation point: no-op unless ``site`` is armed.

    ``raise`` → ``FaultInjected``; ``delay`` → sleep ``delay_s`` (capped
    at the caller's remaining deadline, then the deadline check is the
    caller's to make); ``hang`` → block until the deadline expires
    (raising ``DeadlineExceeded``), the site is disarmed, or ``hang_s``
    elapses (raising ``FaultInjected`` so no caller wedges forever)."""
    if not _env_loaded:
        load_env()
    if _armed_count == 0:
        return
    with _lock:
        spec = _sites.get(site)
        if spec is None:
            return
        if spec.times is not None and spec.fired >= spec.times:
            return
        if spec.p < 1.0 and spec.rng.random() >= spec.p:
            return
        spec.fired += 1
        mode = spec.mode
        delay_s = spec.delay_s
        hang_s = spec.hang_s
        disarmed = spec.disarmed
    _fired_counter(site, mode).inc()
    if mode == "raise":
        raise FaultInjected(site)
    if mode == "delay":
        if deadline is not None:
            delay_s = min(delay_s, max(0.0, deadline.remaining_s()) + 0.01)
        time.sleep(delay_s)
        return
    # hang: block in short slices so disarm()/deadline can release us
    t_end = time.monotonic() + hang_s
    while True:
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded(site)
        if disarmed.wait(timeout=0.01):
            return
        if time.monotonic() >= t_end:
            raise FaultInjected(site)


def load_env(value: Optional[str] = None) -> List[str]:
    """Parse ``PATHWAY_FAULTS`` (or an explicit spec string) and arm the
    sites it names.  Syntax — ``;``- or ``,``-separated entries::

        site=mode[:key=val[:key=val...]]
        PATHWAY_FAULTS="ivf.dispatch=raise:p=0.01:seed=7;exchange.send=delay:ms=50"

    keys: ``p`` (probability), ``times`` (trigger budget), ``ms``
    (delay/hang duration), ``hang_ms`` (hang cap), ``seed``.  Returns
    the list of armed site names (tests use it to assert parsing)."""
    global _env_loaded
    _env_loaded = True
    if value is not None:
        raw = value
    else:
        from .. import config

        raw = config.get("robust.faults")
    armed_sites: List[str] = []
    for entry in raw.replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, _, rest = entry.partition("=")
        parts = rest.split(":") if rest else ["raise"]
        mode = parts[0].strip() or "raise"
        kwargs: Dict[str, Any] = {}
        for opt in parts[1:]:
            k, _, v = opt.partition("=")
            k = k.strip()
            if k == "p":
                kwargs["p"] = float(v)
            elif k == "times":
                kwargs["times"] = int(v)
            elif k == "ms":
                if mode == "hang":
                    kwargs["hang_s"] = float(v) * 1e-3
                else:
                    kwargs["delay_s"] = float(v) * 1e-3
            elif k == "hang_ms":
                kwargs["hang_s"] = float(v) * 1e-3
            elif k == "seed":
                kwargs["seed"] = int(v)
        arm(site.strip(), mode, **kwargs)
        armed_sites.append(site.strip())
    return armed_sites
