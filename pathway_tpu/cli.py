"""``pathway-tpu`` command line — multi-process launcher
(reference: python/pathway/cli.py:53-260 — ``pathway spawn`` /
``pathway replay`` / ``pathway spawn-from-env``).

The reference spawns N engine processes that form a timely cluster over
TCP (PATHWAY_PROCESS_ID / PATHWAY_PROCESSES / PATHWAY_FIRST_PORT).  The
TPU-native analog launches the same user program once per host process; each
process's ``pw.run()`` consumes the exported topology via
``pathway_tpu.parallel.distributed.maybe_initialize()`` — process 0 hosts
the jax coordination service at PATHWAY_COORDINATOR_ADDRESS and the
processes form ONE global device mesh (collectives over ICI/DCN, gloo on
CPU) instead of a socket cluster.  See parallel/distributed.py for the
execution model and tests/test_distributed.py for the 2-process parity
tests.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

from . import config

__all__ = ["main", "spawn_program"]


def _topology_env(
    process_id: int,
    processes: int,
    first_port: int,
    base: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    env = dict(os.environ if base is None else base)
    env["PATHWAY_PROCESS_ID"] = str(process_id)
    env["PATHWAY_PROCESSES"] = str(processes)
    env["PATHWAY_FIRST_PORT"] = str(first_port)
    # consumed by parallel/distributed.maybe_initialize() (called from
    # pw.run()): process 0 hosts the jax coordination service here
    env["PATHWAY_COORDINATOR_ADDRESS"] = f"127.0.0.1:{first_port}"
    return env


def spawn_program(
    program: str,
    arguments: Sequence[str],
    *,
    processes: int = 1,
    first_port: int = 10000,
    env_extra: Optional[Dict[str, str]] = None,
    timeout: Optional[float] = None,
) -> int:
    """Launch ``processes`` copies of ``program``; returns the first
    non-zero exit code observed (the teardown cause), or 0 if all succeed.
    A failing process tears the others down (the reference's
    all-pods-must-be-present model, SURVEY §5.3).  ``timeout`` (seconds):
    kill anything still running then; returns 124 only when the timeout is
    the first failure (an earlier member's non-zero code wins)."""
    handles: List[subprocess.Popen] = []
    try:
        for pid in range(processes):
            env = _topology_env(pid, processes, first_port)
            if env_extra:
                env.update(env_extra)
            handles.append(
                subprocess.Popen([program, *arguments], env=env)
            )
        # wait on ANY process: a crashed member must tear the others down
        # immediately, even while lower-index members are still running
        import time as _time

        deadline = _time.time() + timeout if timeout else None
        exit_code = 0
        live = list(handles)
        terminated = False
        while live:
            progressed = False
            for h in list(live):
                rc = h.poll()
                if rc is None:
                    continue
                live.remove(h)
                progressed = True
                if rc != 0 and not terminated:
                    exit_code = rc
                    terminated = True
                    for other in live:
                        if other.poll() is None:
                            other.send_signal(signal.SIGTERM)
            if live and deadline is not None and _time.time() > deadline:
                for h in live:
                    if h.poll() is None:
                        h.kill()
                for h in live:
                    h.wait()
                # keep an already-observed failure code as the cause; 124
                # only when the timeout itself is the first failure
                return exit_code or 124
            if live and not progressed:
                _time.sleep(0.05)
        return exit_code
    except KeyboardInterrupt:
        for h in handles:
            if h.poll() is None:
                h.send_signal(signal.SIGINT)
        for h in handles:
            h.wait()
        return 130


def run_template(
    template: str,
    host: Optional[str] = None,
    port: Optional[int] = None,
) -> int:
    """Load a YAML template app (the L7 surface — reference template apps,
    docs/2.developers/7.templates/) and serve it: a ``question_answerer``
    gets the QA REST routes, a bare ``document_store`` the retrieval routes,
    and a plain pipeline just runs."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # the TPU plugin registers at interpreter startup (sitecustomize);
        # honor the env var by flipping the config before first backend use
        import jax

        jax.config.update("jax_platforms", "cpu")
    from pathway_tpu.internals.yaml_loader import load_yaml

    with open(template) as f:
        cfg = load_yaml(f)
    if not isinstance(cfg, dict):
        raise SystemExit(f"template {template} must be a mapping, got {type(cfg)}")
    host = host or cfg.get("host", "127.0.0.1")
    port = port or int(cfg.get("port", 8000))

    qa = cfg.get("question_answerer")
    if qa is not None:
        qa.build_server(host=host, port=port)
        print(f"serving QA endpoints at http://{host}:{port}", flush=True)
        qa.run_server(with_cache=False)
        return 0
    store = cfg.get("document_store")
    if store is not None:
        from pathway_tpu.xpacks.llm.servers import DocumentStoreServer

        server = DocumentStoreServer(host, port, store)
        print(f"serving DocumentStore at http://{host}:{port}", flush=True)
        server.run(with_cache=False)
        return 0
    import pathway_tpu as pw

    pw.run()
    return 0


def _persistence_env(args) -> Dict[str, str]:
    env: Dict[str, str] = {}
    if getattr(args, "record", False) or getattr(args, "mode", None):
        path = getattr(args, "record_path", None) or "./record"
        env["PATHWAY_PERSISTENT_STORAGE"] = path
    if getattr(args, "mode", None):
        env["PATHWAY_PERSISTENCE_MODE"] = args.mode.upper()
    return env


def _add_spawn_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "-n",
        "--processes",
        type=int,
        default=1,
        help="number of host processes to launch",
    )
    p.add_argument(
        "--first-port",
        type=int,
        default=10000,
        help="port of the coordination service hosted by process 0",
    )
    p.add_argument("program")
    p.add_argument("arguments", nargs=argparse.REMAINDER)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="pathway-tpu", description=__doc__.splitlines()[0]
    )
    sub = parser.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("spawn", help="run a program on N coordinated processes")
    _add_spawn_args(sp)
    sp.add_argument(
        "--record", action="store_true", help="record input connector data"
    )
    sp.add_argument(
        "--record-path", default=None, help="snapshot storage location"
    )

    rp = sub.add_parser("replay", help="re-run a program from recorded data")
    _add_spawn_args(rp)
    rp.add_argument(
        "--record-path", default="./record", help="snapshot storage location"
    )
    rp.add_argument(
        "--mode",
        choices=["batch", "speedrun"],
        default="batch",
        help="replay timing: batch (collapse) or speedrun (original pacing)",
    )

    se = sub.add_parser(
        "spawn-from-env",
        help="spawn with arguments taken from $PATHWAY_SPAWN_ARGS",
    )
    se.add_argument("program", nargs="?", default=None)
    se.add_argument("arguments", nargs=argparse.REMAINDER)

    rn = sub.add_parser(
        "run", help="run a YAML template app (see templates/)"
    )
    rn.add_argument("template", help="path to a template YAML")
    rn.add_argument("--host", default=None, help="override the template host")
    rn.add_argument(
        "--port", type=int, default=None, help="override the template port"
    )

    args = parser.parse_args(argv)

    if args.command == "run":
        return run_template(args.template, host=args.host, port=args.port)

    if args.command == "spawn-from-env":
        spawn_args = shlex.split(config.get("cli.spawn_args"))
        extra = [args.program] if args.program else []
        return main(["spawn", *spawn_args, *extra, *args.arguments])

    env_extra = _persistence_env(args)
    return spawn_program(
        args.program,
        args.arguments,
        processes=args.processes,
        first_port=args.first_port,
        env_extra=env_extra,
    )


if __name__ == "__main__":
    sys.exit(main())
