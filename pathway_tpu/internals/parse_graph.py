"""Global graph state.

The reference keeps a global ``ParseGraph`` of user operators that a
GraphRunner later lowers onto the engine (python/pathway/internals/
parse_graph.py:104, graph_runner/__init__.py:36).  Here the Table API lowers
*eagerly* onto the engine graph (the DAG of columnar-delta operators in
engine/graph.py); this module holds that graph plus run bookkeeping.
"""

from __future__ import annotations

from typing import List, Optional

from ..engine.graph import EngineGraph

__all__ = ["G", "GraphHolder"]


class GraphHolder:
    def __init__(self):
        self.engine_graph = EngineGraph()
        self.ran = False
        # operator ids already executed by a previous run() — later runs
        # bootstrap newly added operators from upstream stores
        self.ran_ops: set = set()
        # callables invoked before run (e.g. connector thread starters);
        # each fires exactly once
        self.pre_run_hooks: List = []
        self.hooks_started: int = 0
        # callables invoked after run finishes
        self.post_run_hooks: List = []
        # per-graph build ordinals (e.g. kafka read #) — deterministic
        # across ranks because every rank builds the same graph in the same
        # order, and reset with the graph (unlike module-level counters,
        # which would drift on notebook re-runs / second graphs)
        self.io_ordinals: dict = {}

    def clear(self) -> None:
        self.engine_graph = EngineGraph()
        self.ran = False
        self.ran_ops = set()
        self.pre_run_hooks = []
        self.hooks_started = 0
        self.post_run_hooks = []
        self.io_ordinals = {}

    def claim_io_ordinal(self, kind: str) -> int:
        n = self.io_ordinals.get(kind, 0)
        self.io_ordinals[kind] = n + 1
        return n


G = GraphHolder()
