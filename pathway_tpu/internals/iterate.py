"""pw.iterate — fixed-point iteration.

Reference: pw.iterate (python/pathway/internals/common.py:39) backed by nested
iterative scopes in the engine (src/engine/graph.rs:941 Graph::iterate,
src/engine/dataflow.rs:3737 — timely nested scopes with iteration_limit).

TPU-native design: the iteration body is built ONCE as a nested engine
subgraph with its own sources; at each outer commit tick the operator pushes
the outer input delta into the nested sources, then repeatedly steps the
nested *incremental* executor, feeding the difference between the body's
output and its input back into the sources until the difference is empty
(fixed point) or ``iteration_limit`` is hit.  Because the nested engine is
itself incremental, iteration k only recomputes what changed in iteration
k-1 — the same work profile as the reference's differential nested scopes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..engine.delta import Delta
from ..engine.executor import Executor, next_timestamp
from ..engine.graph import EngineGraph, EngineOperator, EngineTable
from ..engine.operators.io import InputSession, SourceOperator
from .parse_graph import G
from .universe import Universe

__all__ = ["iterate", "iterate_universe"]


def iterate_universe(table):
    """Marks an iterate argument whose key set may change between iterations
    (reference: pw.iterate_universe, internals/common.py).  This engine's
    iterate always allows the key set to evolve, so this is identity —
    kept for API parity."""
    return table


class _IterateOperator(EngineOperator):
    """Outer operator owning the nested subgraph (multi-output: emits via
    on_tick_end returning [(table, delta), ...])."""

    def __init__(
        self,
        inputs: List[EngineTable],
        input_names: List[str],
        input_mappings: List[Dict[str, str]],  # api col -> outer engine col
        sessions: Dict[str, InputSession],
        nested_graph: EngineGraph,
        nested_inputs: Dict[str, EngineTable],
        nested_outputs: Dict[str, Tuple[EngineTable, Dict[str, str]]],
        outer_outputs: Dict[str, EngineTable],
        feedback_names: List[str],
        iteration_limit: Optional[int],
        name: str = "iterate",
    ):
        super().__init__(inputs, None, name)
        self.input_names = input_names
        self.input_mappings = input_mappings
        self.sessions = sessions
        self.nested_graph = nested_graph
        self.nested_inputs = nested_inputs
        self.nested_outputs = nested_outputs
        self.outer_outputs = outer_outputs
        self.feedback_names = feedback_names
        self.iteration_limit = iteration_limit
        self.nested_graph.finalize()
        self.nested_exec = Executor(self.nested_graph)
        self._buffered: List[Tuple[int, Delta]] = []

    def process(self, port: int, delta: Delta, ts: int) -> Optional[Delta]:
        if delta.n:
            self._buffered.append((port, delta))
        return None

    def snapshot_state(self):
        """Nested-subgraph state for OPERATOR_PERSISTING: all nested table
        stores plus nested stateful-operator state (recursing through any
        inner iterates via the same hooks)."""
        op_states = {}
        for i, op in enumerate(self.nested_graph.operators):
            try:
                op_states[i] = op.snapshot_state()
            except NotImplementedError:
                pass
        return {
            "tables": [dict(t.store._rows) for t in self.nested_graph.tables],
            "ops": op_states,
        }

    def restore_state(self, state) -> None:
        for table, rows in zip(self.nested_graph.tables, state["tables"]):
            table.store._rows = dict(rows)
        for i, op_state in state["ops"].items():
            self.nested_graph.operators[i].restore_state(op_state)

    # -- helpers -----------------------------------------------------------
    def _push_outer_delta(self, port: int, delta: Delta) -> None:
        name = self.input_names[port]
        session = self.sessions[name]
        mapping = self.input_mappings[port]
        api_cols = list(self.nested_inputs[name].column_names)
        cols = [delta.columns[mapping[c]] for c in api_cols]
        for i in range(delta.n):
            row = tuple(c[i] for c in cols)
            if delta.diffs[i] > 0:
                session.insert(int(delta.keys[i]), row)
            else:
                session.remove(int(delta.keys[i]), row)

    def _feedback(self) -> bool:
        """Push (output - input) into the nested sources; False at fixpoint."""
        changed = False
        for name in self.feedback_names:
            out_table, out_mapping = self.nested_outputs[name]
            in_table = self.nested_inputs[name]
            session = self.sessions[name]
            api_cols = list(in_table.column_names)
            idx = [out_table.column_names.index(out_mapping[c]) for c in api_cols]
            target: Dict[int, tuple] = {}
            for key, row in out_table.store.items():
                target[key] = tuple(row[i] for i in idx)
            current = {key: tuple(row) for key, row in in_table.store.items()}
            for key, row in current.items():
                if key not in target:
                    session.remove(key, row)
                    changed = True
            for key, row in target.items():
                old = current.get(key)
                if old is None:
                    session.insert(key, row)
                    changed = True
                elif not _tuples_equal(old, row):
                    session.remove(key, old)
                    session.insert(key, row)
                    changed = True
        return changed

    def on_tick_end(self, ts: int) -> Optional[list]:
        if not self._buffered:
            return None
        buffered, self._buffered = self._buffered, []
        for port, delta in buffered:
            self._push_outer_delta(port, delta)
        limit = self.iteration_limit or 2**31
        for _ in range(limit):
            self.nested_exec.step(next_timestamp())
            if not self._feedback():
                break
        else:
            # push the last feedback through so outputs reflect the final
            # allowed iteration
            self.nested_exec.step(next_timestamp())
        # emit diffs of each nested output vs the outer output tables
        emissions = []
        for name, (out_table, out_mapping) in self.nested_outputs.items():
            outer = self.outer_outputs[name]
            api_cols = list(outer.column_names)
            idx = [out_table.column_names.index(out_mapping[c]) for c in api_cols]
            target = {
                key: tuple(row[i] for i in idx)
                for key, row in out_table.store.items()
            }
            current = {key: tuple(row) for key, row in outer.store.items()}
            rows: List[Tuple[int, int, tuple]] = []
            for key, row in current.items():
                if key not in target or not _tuples_equal(target[key], row):
                    rows.append((key, -1, row))
            for key, row in target.items():
                old = current.get(key)
                if old is None or not _tuples_equal(old, row):
                    rows.append((key, 1, row))
            if rows:
                emissions.append((outer, Delta.from_rows(api_cols, rows)))
        return emissions or None


def _tuples_equal(a: tuple, b: tuple) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x is y:
            continue
        try:
            if x != y:
                return False
        except Exception:
            return False
    return True


def iterate(func, iteration_limit: Optional[int] = None, **kwargs):
    """Iterate ``func`` to fixed point.

    ``kwargs`` are passed to ``func``; Table arguments iterate.  ``func``
    must return a Table, a tuple of Tables, or a dict of Tables; returned
    tables whose names match input kwargs feed back into the next iteration.
    A single-table return with a single table input always feeds back.
    """
    from .table import Table

    table_inputs = {k: v for k, v in kwargs.items() if isinstance(v, Table)}
    if not table_inputs:
        raise ValueError("pw.iterate needs at least one Table argument")

    # build the iteration body against a fresh nested graph
    outer_graph = G.engine_graph
    nested_graph = EngineGraph()
    G.engine_graph = nested_graph
    try:
        placeholders: Dict[str, Any] = dict(kwargs)
        sessions: Dict[str, InputSession] = {}
        nested_inputs: Dict[str, EngineTable] = {}
        for name, t in table_inputs.items():
            api_cols = t.column_names
            et = nested_graph.add_table(api_cols, f"iter_in_{name}")
            session = InputSession()
            nested_graph.add_operator(
                SourceOperator(et, session, t._dtypes, name=f"iter_src_{name}")
            )
            sessions[name] = session
            nested_inputs[name] = et
            placeholders[name] = Table(
                et, t._dtypes, Universe(), short_name=f"iter_{name}"
            )
        result = func(**placeholders)
    finally:
        G.engine_graph = outer_graph

    # normalize the returned structure
    single = isinstance(result, Table)
    if single:
        only_name = next(iter(table_inputs))
        result_dict: Dict[str, Table] = {only_name: result}
    elif isinstance(result, dict):
        result_dict = dict(result)
    elif isinstance(result, tuple):
        result_dict = {
            name: res for name, res in zip(table_inputs.keys(), result)
        }
    else:
        raise TypeError(
            f"pw.iterate body must return Table/tuple/dict, got {type(result)}"
        )
    for name, res in result_dict.items():
        if not isinstance(res, Table):
            raise TypeError(f"iterate output {name!r} is not a Table")

    nested_outputs = {
        name: (res._engine_table, dict(res._column_mapping))
        for name, res in result_dict.items()
    }
    feedback_names = [n for n in result_dict if n in table_inputs]

    input_names = list(table_inputs.keys())
    outer_inputs = [table_inputs[n]._engine_table for n in input_names]
    input_mappings = [dict(table_inputs[n]._column_mapping) for n in input_names]
    outer_outputs: Dict[str, EngineTable] = {}
    out_tables: Dict[str, Table] = {}
    for name, res in result_dict.items():
        et = outer_graph.add_table(res.column_names, f"iterate_{name}")
        outer_outputs[name] = et
        out_tables[name] = Table(et, res._dtypes, Universe(), short_name=f"iterate_{name}")

    outer_graph.add_operator(
        _IterateOperator(
            outer_inputs,
            input_names,
            input_mappings,
            sessions,
            nested_graph,
            nested_inputs,
            nested_outputs,
            outer_outputs,
            feedback_names,
            iteration_limit,
        )
    )

    if single:
        return out_tables[next(iter(table_inputs))]
    if isinstance(result, tuple):
        return tuple(out_tables[n] for n in result_dict)
    return out_tables
