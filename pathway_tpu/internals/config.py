"""Environment-first configuration
(reference: python/pathway/internals/config.py:58-80 — PathwayConfig env
fields; src/engine/dataflow/config.rs — topology env vars).

All env parsing goes through the declarative registry
(``pathway_tpu/config.py``) — field defaults are ``default_factory``
thunks, so each ``PathwayConfig()`` construction reads the CURRENT knob
values instead of whatever the env held at class-definition time."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .. import config

__all__ = ["PathwayConfig", "get_config", "set_license_key", "local_config"]


@dataclass
class PathwayConfig:
    # mesh/topology (the TPU analog of PATHWAY_THREADS/PROCESSES)
    mesh_data_axis: int = field(
        default_factory=lambda: config.get("parallel.data_shards")
    )
    mesh_model_axis: int = field(
        default_factory=lambda: config.get("parallel.model_shards")
    )
    # engine
    commit_duration_ms: int = field(
        default_factory=lambda: config.get("engine.commit_duration_ms")
    )
    terminate_on_error: bool = field(
        default_factory=lambda: config.get("engine.terminate_on_error")
    )
    runtime_typechecking: bool = field(
        default_factory=lambda: config.get("engine.runtime_typechecking")
    )
    # persistence
    persistence_mode: str = field(
        default_factory=lambda: config.get("persistence.mode")
    )
    replay_storage: Optional[str] = field(
        default_factory=lambda: config.get("persistence.replay_storage") or None
    )
    persistent_storage: Optional[str] = field(
        default_factory=lambda: config.get("persistence.storage") or None
    )
    snapshot_interval_ms: int = field(
        default_factory=lambda: config.get("persistence.snapshot_interval_ms")
    )
    # observability
    monitoring_server: Optional[str] = field(
        default_factory=lambda: config.get("observe.monitoring_server") or None
    )
    metrics_port: int = field(
        default_factory=lambda: config.get("observe.metrics_port")
    )
    metrics_host: str = field(
        default_factory=lambda: config.get("observe.metrics_host")
    )
    # licensing: this framework is fully open — accepted and ignored
    license_key: Optional[str] = field(
        default_factory=lambda: config.get("license.key") or None
    )

    @property
    def process_id(self) -> int:
        return config.get("parallel.process_id")

    @property
    def processes(self) -> int:
        return config.get("parallel.processes")


_config = PathwayConfig()


def get_config() -> PathwayConfig:
    return _config


def set_license_key(key: Optional[str]) -> None:
    """Reference-compat no-op: pathway_tpu has no license gating
    (reference: license.rs:31 gates >8 workers; here the mesh is the limit)."""
    _config.license_key = key


def set_monitoring_config(*, server_endpoint: Optional[str]) -> None:
    """Set (or clear) the OTLP monitoring endpoint consumed by
    internals/telemetry.py (reference internals/config.py:144
    ``set_monitoring_config``; no license gating here)."""
    _config.monitoring_server = server_endpoint


class local_config:
    def __init__(self, **overrides):
        self.overrides = overrides
        self._saved = {}

    def __enter__(self):
        for k, v in self.overrides.items():
            self._saved[k] = getattr(_config, k)
            setattr(_config, k, v)
        return _config

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            setattr(_config, k, v)
        return False
