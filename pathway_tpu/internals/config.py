"""Environment-first configuration
(reference: python/pathway/internals/config.py:58-80 — PathwayConfig env
fields; src/engine/dataflow/config.rs — topology env vars)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["PathwayConfig", "get_config", "set_license_key", "local_config"]


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


@dataclass
class PathwayConfig:
    # mesh/topology (the TPU analog of PATHWAY_THREADS/PROCESSES)
    mesh_data_axis: int = int(os.environ.get("PATHWAY_TPU_DATA_SHARDS", "0") or 0)
    mesh_model_axis: int = int(os.environ.get("PATHWAY_TPU_MODEL_SHARDS", "0") or 0)
    # engine
    commit_duration_ms: int = int(os.environ.get("PATHWAY_COMMIT_DURATION_MS", "100"))
    terminate_on_error: bool = _env_bool("PATHWAY_TERMINATE_ON_ERROR", True)
    runtime_typechecking: bool = _env_bool("PATHWAY_RUNTIME_TYPECHECKING", False)
    # persistence
    persistence_mode: str = os.environ.get("PATHWAY_PERSISTENCE_MODE", "")
    replay_storage: Optional[str] = os.environ.get("PATHWAY_REPLAY_STORAGE")
    persistent_storage: Optional[str] = os.environ.get("PATHWAY_PERSISTENT_STORAGE")
    snapshot_interval_ms: int = int(
        os.environ.get("PATHWAY_SNAPSHOT_INTERVAL_MS", "60000")
    )
    # observability
    monitoring_server: Optional[str] = os.environ.get("PATHWAY_MONITORING_SERVER")
    metrics_port: int = int(os.environ.get("PATHWAY_METRICS_PORT", "20000"))
    metrics_host: str = os.environ.get("PATHWAY_METRICS_HOST", "127.0.0.1")
    # licensing: this framework is fully open — accepted and ignored
    license_key: Optional[str] = os.environ.get("PATHWAY_LICENSE_KEY")

    @property
    def process_id(self) -> int:
        return int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    @property
    def processes(self) -> int:
        return int(os.environ.get("PATHWAY_PROCESSES", "1"))


_config = PathwayConfig()


def get_config() -> PathwayConfig:
    return _config


def set_license_key(key: Optional[str]) -> None:
    """Reference-compat no-op: pathway_tpu has no license gating
    (reference: license.rs:31 gates >8 workers; here the mesh is the limit)."""
    _config.license_key = key


def set_monitoring_config(*, server_endpoint: Optional[str]) -> None:
    """Set (or clear) the OTLP monitoring endpoint consumed by
    internals/telemetry.py (reference internals/config.py:144
    ``set_monitoring_config``; no license gating here)."""
    _config.monitoring_server = server_endpoint


class local_config:
    def __init__(self, **overrides):
        self.overrides = overrides
        self._saved = {}

    def __enter__(self):
        for k, v in self.overrides.items():
            self._saved[k] = getattr(_config, k)
            setattr(_config, k, v)
        return _config

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            setattr(_config, k, v)
        return False
