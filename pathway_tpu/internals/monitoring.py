"""Terminal monitoring dashboard
(reference: python/pathway/internals/monitoring.py:56-280 — rich-based stats
monitor of connector lag and operator latencies)."""

from __future__ import annotations

import enum
import sys
import time
from typing import Optional

__all__ = ["MonitoringLevel", "StatsMonitor"]


class MonitoringLevel(enum.Enum):
    AUTO = 0
    AUTO_ALL = 1
    NONE = 2
    IN_OUT = 3
    ALL = 4


class StatsMonitor:
    """Lightweight periodic stats printer; rich dashboard when attached to a
    tty."""

    def __init__(self, engine_graph, refresh_s: float = 2.0):
        self.graph = engine_graph
        self.refresh_s = refresh_s
        self._last = 0.0
        self._rows_seen = 0

    def on_tick(self, ts: int) -> None:
        now = time.time()
        if now - self._last < self.refresh_s:
            return
        self._last = now
        total_rows = sum(len(t.store) for t in self.graph.tables)
        n_ops = len(self.graph.operators)
        print(
            f"[pathway_tpu] ts={ts} operators={n_ops} resident_rows={total_rows}",
            file=sys.stderr,
        )
