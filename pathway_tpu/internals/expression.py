"""Lazy column expressions.

The user-facing expression tree (reference:
python/pathway/internals/expression.py) — built by operating on
``table.col`` / ``pw.this.col`` references — evaluated here *columnar-vectorized*
over micro-batches instead of the reference's per-row interpreter
(src/engine/expression.rs:26-325).  Dense numeric columns evaluate as numpy /
jax array ops (fusible by XLA when the enclosing operator is jitted); object
columns fall back to a per-row loop.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, Iterable, Mapping, Optional, Sequence, Tuple

import numpy as np

from . import dtype as dt

__all__ = [
    "ColumnExpression",
    "ColumnReference",
    "ColumnConstExpression",
    "ColumnBinaryOpExpression",
    "ColumnUnaryOpExpression",
    "ApplyExpression",
    "AsyncApplyExpression",
    "IfElseExpression",
    "IsNoneExpression",
    "IsNotNoneExpression",
    "CastExpression",
    "ConvertExpression",
    "CoalesceExpression",
    "RequireExpression",
    "PointerExpression",
    "ReducerExpression",
    "MakeTupleExpression",
    "GetExpression",
    "MethodCallExpression",
    "IdExpression",
    "smart_coerce",
]


class ColumnExpression:
    """Base of the expression tree."""

    _deps: Tuple["ColumnExpression", ...] = ()

    # -- operator overloads ------------------------------------------------
    def _bin(self, other, op, symbol, reflected=False):
        other = smart_coerce(other)
        if reflected:
            return ColumnBinaryOpExpression(other, self, op, symbol)
        return ColumnBinaryOpExpression(self, other, op, symbol)

    def __add__(self, other):
        return self._bin(other, operator.add, "+")

    def __radd__(self, other):
        return self._bin(other, operator.add, "+", True)

    def __sub__(self, other):
        return self._bin(other, operator.sub, "-")

    def __rsub__(self, other):
        return self._bin(other, operator.sub, "-", True)

    def __mul__(self, other):
        return self._bin(other, operator.mul, "*")

    def __rmul__(self, other):
        return self._bin(other, operator.mul, "*", True)

    def __truediv__(self, other):
        return self._bin(other, operator.truediv, "/")

    def __rtruediv__(self, other):
        return self._bin(other, operator.truediv, "/", True)

    def __floordiv__(self, other):
        return self._bin(other, operator.floordiv, "//")

    def __rfloordiv__(self, other):
        return self._bin(other, operator.floordiv, "//", True)

    def __mod__(self, other):
        return self._bin(other, operator.mod, "%")

    def __rmod__(self, other):
        return self._bin(other, operator.mod, "%", True)

    def __pow__(self, other):
        return self._bin(other, operator.pow, "**")

    def __rpow__(self, other):
        return self._bin(other, operator.pow, "**", True)

    def __matmul__(self, other):
        return self._bin(other, operator.matmul, "@")

    def __rmatmul__(self, other):
        return self._bin(other, operator.matmul, "@", True)

    def __and__(self, other):
        return self._bin(other, operator.and_, "&")

    def __rand__(self, other):
        return self._bin(other, operator.and_, "&", True)

    def __or__(self, other):
        return self._bin(other, operator.or_, "|")

    def __ror__(self, other):
        return self._bin(other, operator.or_, "|", True)

    def __xor__(self, other):
        return self._bin(other, operator.xor, "^")

    def __rxor__(self, other):
        return self._bin(other, operator.xor, "^", True)

    def __eq__(self, other):  # type: ignore[override]
        return self._bin(other, operator.eq, "==")

    def __ne__(self, other):  # type: ignore[override]
        return self._bin(other, operator.ne, "!=")

    def __lt__(self, other):
        return self._bin(other, operator.lt, "<")

    def __le__(self, other):
        return self._bin(other, operator.le, "<=")

    def __gt__(self, other):
        return self._bin(other, operator.gt, ">")

    def __ge__(self, other):
        return self._bin(other, operator.ge, ">=")

    def __neg__(self):
        return ColumnUnaryOpExpression(self, operator.neg, "-")

    def __invert__(self):
        return ColumnUnaryOpExpression(self, operator.not_, "~")

    def __abs__(self):
        return ColumnUnaryOpExpression(self, operator.abs, "abs")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise TypeError(
            "ColumnExpression is lazy and cannot be used as a bool; "
            "use &, |, ~ instead of and/or/not"
        )

    # -- convenience methods ----------------------------------------------
    def is_none(self) -> "IsNoneExpression":
        return IsNoneExpression(self)

    def is_not_none(self) -> "IsNotNoneExpression":
        return IsNotNoneExpression(self)

    def get(self, index, default=None) -> "GetExpression":
        return GetExpression(self, smart_coerce(index), smart_coerce(default), check=True)

    def __getitem__(self, index) -> "GetExpression":
        return GetExpression(self, smart_coerce(index), None, check=False)

    def to_string(self) -> "MethodCallExpression":
        return MethodCallExpression(
            "to_string", (self,), lambda v: "" if v is None else str(v), dt.STR
        )

    def as_int(self):
        return ConvertExpression(self, dt.INT)

    def as_float(self):
        return ConvertExpression(self, dt.FLOAT)

    def as_str(self):
        return ConvertExpression(self, dt.STR)

    def as_bool(self):
        return ConvertExpression(self, dt.BOOL)

    # namespaces (populated in expressions/ modules)
    @property
    def dt(self):
        from .expressions.date_time import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self):
        from .expressions.string import StringNamespace

        return StringNamespace(self)

    @property
    def num(self):
        from .expressions.numerical import NumericalNamespace

        return NumericalNamespace(self)

    # -- evaluation --------------------------------------------------------
    def _eval(self, ctx: "EvalContext") -> np.ndarray:
        raise NotImplementedError

    @property
    def _dependencies(self) -> Iterable["ColumnExpression"]:
        return self._deps

    def _column_refs(self) -> Iterable["ColumnReference"]:
        """All ColumnReferences in the tree."""
        if isinstance(self, ColumnReference):
            yield self
        for dep in self._deps:
            if dep is not None:
                yield from dep._column_refs()


class EvalContext:
    """Columns of the current micro-batch being evaluated.

    ``columns`` maps (table_id, column_name) → np array of row values;
    ``keys`` is the row-key vector; ``n`` the number of rows."""

    def __init__(self, columns: Mapping[Tuple[int, str], np.ndarray], keys: np.ndarray):
        self.columns = columns
        self.keys = keys
        self.n = len(keys)

    def lookup(self, table_id: int, name: str) -> np.ndarray:
        return self.columns[(table_id, name)]


def smart_coerce(value: Any) -> Any:
    if isinstance(value, ColumnExpression) or value is None:
        return value
    return ColumnConstExpression(value)


def _is_object(arr: np.ndarray) -> bool:
    return arr.dtype == object


def _rowwise(fn, *arrays, n: int, trace=None) -> np.ndarray:
    """Per-row loop with reference error semantics: a failing row yields an
    Error cell instead of aborting the batch (Value::Error,
    /root/reference/src/engine/value.rs:225).  ``trace`` (the expression's
    build-site user frame) flows into the Error message and the error log."""
    from .error_value import ERROR, Error, is_error

    out = np.empty(n, dtype=object)
    for i in range(n):
        args = tuple(a[i] for a in arrays)
        if any(is_error(a) for a in args):
            out[i] = ERROR
            continue
        try:
            out[i] = fn(*args)
        except Exception as e:
            from .error_log import log_error

            message = f"{type(e).__name__}: {e}"
            if trace is not None:
                message = f"{message} (expression built at {trace})"
            log_error(message, operator="expression", trace=trace)
            out[i] = Error(message)
    return out


class ColumnReference(ColumnExpression):
    """Reference to ``table.column_name``."""

    def __init__(self, table, name: str):
        self._table = table
        self._name = name

    @property
    def table(self):
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self):
        return f"<{getattr(self._table, '_short_name', 'table')}.{self._name}>"

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        return ctx.lookup(id(self._table), self._name)


class IdExpression(ColumnExpression):
    """``table.id`` — the key column.  In contexts that carry per-side row
    ids under the ``__id__`` pseudo-column (join selects: the joined output
    has its own keys, but ``left.id``/``right.id`` must mean the *side's*
    row ids), the bound table's entry wins over the ambient keys."""

    def __init__(self, table):
        self._table = table

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        side = ctx.columns.get((id(self._table), "__id__"))
        if side is not None:
            return side
        return ctx.keys


class ColumnConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self._value = value

    def __repr__(self):
        return f"const({self._value!r})"

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        v = self._value
        npdt = dt.numpy_dtype_for(dt.dtype_of_value(v))
        if npdt is not None:
            return np.full(ctx.n, v, dtype=npdt)
        out = np.empty(ctx.n, dtype=object)
        out[:] = [v] * ctx.n
        return out


_FLOAT_DIV_OPS = {operator.truediv}


class ColumnBinaryOpExpression(ColumnExpression):
    def __init__(self, left, right, op, symbol: str):
        from .trace import trace_user_frame

        self._left = smart_coerce(left)
        self._right = smart_coerce(right)
        self._op = op
        self._symbol = symbol
        self._deps = (self._left, self._right)
        self._trace = trace_user_frame()

    def __repr__(self):
        return f"({self._left!r} {self._symbol} {self._right!r})"

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        l = self._left._eval(ctx)
        r = self._right._eval(ctx)
        op = self._op
        if _is_object(l) or _is_object(r):
            if op in (operator.and_, operator.or_):
                # python bools use and/or semantics on object columns
                pyop = (lambda a, b: a and b) if op is operator.and_ else (lambda a, b: a or b)
                return _rowwise(pyop, l, r, n=ctx.n, trace=self._trace)
            return _rowwise(op, l, r, n=ctx.n, trace=self._trace)
        try:
            if op is operator.floordiv and np.issubdtype(l.dtype, np.integer):
                if np.any(r == 0):
                    raise ZeroDivisionError("integer division by zero")
            if op is operator.mod and np.issubdtype(l.dtype, np.integer) and np.any(r == 0):
                raise ZeroDivisionError("integer modulo by zero")
            return op(l, r)
        except TypeError:
            return _rowwise(op, l, r, n=ctx.n, trace=self._trace)


class ColumnUnaryOpExpression(ColumnExpression):
    def __init__(self, expr, op, symbol: str):
        from .trace import trace_user_frame

        self._expr = smart_coerce(expr)
        self._op = op
        self._symbol = symbol
        self._deps = (self._expr,)
        self._trace = trace_user_frame()

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        v = self._expr._eval(ctx)
        if self._op is operator.not_:
            if _is_object(v):
                return _rowwise(lambda x: not x, v, n=ctx.n, trace=self._trace)
            return ~v.astype(bool)
        if _is_object(v):
            return _rowwise(self._op, v, n=ctx.n, trace=self._trace)
        return self._op(v)


class ApplyExpression(ColumnExpression):
    """Per-row python function application (``pw.apply`` / sync UDF).

    ``batched=True`` functions receive whole column arrays at once — the
    TPU-idiomatic form for ML UDFs (see SURVEY.md §7.6)."""

    def __init__(
        self,
        fun: Callable,
        return_type: Any,
        args: Sequence[Any] = (),
        kwargs: Mapping[str, Any] | None = None,
        batched: bool = False,
        propagate_none: bool = False,
    ):
        self._fun = fun
        self._return_type = dt.wrap(return_type) if return_type is not None else dt.ANY
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = {k: smart_coerce(v) for k, v in (kwargs or {}).items()}
        self._batched = batched
        self._propagate_none = propagate_none
        self._deps = self._args + tuple(self._kwargs.values())
        # user frame of the apply/udf call site — failing rows name this line
        # (reference: trace.py frames attached per expression)
        from .trace import trace_user_frame

        self._trace = trace_user_frame()

    def _row_error(self, exc: Exception, op_id: int | None = None):
        from .error_log import log_error
        from .error_value import Error

        fn_name = getattr(self._fun, "__name__", "<udf>")
        loc = f" (udf {fn_name} applied at {self._trace})" if self._trace else ""
        message = f"{type(exc).__name__}: {exc}{loc}"
        log_error(message, operator="apply", trace=self._trace, op_id=op_id)
        return Error(message)

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        arg_arrays = [a._eval(ctx) for a in self._args]
        kwarg_arrays = {k: v._eval(ctx) for k, v in self._kwargs.items()}
        if self._batched:
            result = self._fun(*arg_arrays, **kwarg_arrays)
            if not isinstance(result, np.ndarray):
                try:
                    import jax

                    if isinstance(result, jax.Array):
                        result = np.asarray(result)
                except ImportError:  # pragma: no cover
                    pass
            if not isinstance(result, np.ndarray):
                result = np.asarray(result)
            if result.ndim > 1:
                # batched fn returned [B, ...]: column cells are row slices
                out = np.empty(result.shape[0], dtype=object)
                for i in range(result.shape[0]):
                    out[i] = result[i]
                return out
            return result
        from .error_value import ERROR, Error, is_error

        npdt = dt.numpy_dtype_for(self._return_type)
        out = np.empty(ctx.n, dtype=npdt if npdt is not None else object)
        errored = False
        for i in range(ctx.n):
            args_i = [a[i] for a in arg_arrays]
            kwargs_i = {k: v[i] for k, v in kwarg_arrays.items()}
            if self._propagate_none and (
                any(a is None for a in args_i) or any(v is None for v in kwargs_i.values())
            ):
                out[i] = None
            elif any(is_error(a) for a in args_i):
                errored = True
                if out.dtype == object:
                    out[i] = ERROR
            else:
                try:
                    out[i] = self._fun(*args_i, **kwargs_i)
                except Exception as e:
                    errored = True
                    if out.dtype == object:
                        out[i] = self._row_error(e)
                    else:
                        out[i] = 0
        if errored and out.dtype != object:
            # re-run into an object column so Error cells survive
            out2 = np.empty(ctx.n, dtype=object)
            for i in range(ctx.n):
                args_i = [a[i] for a in arg_arrays]
                kwargs_i = {k: v[i] for k, v in kwarg_arrays.items()}
                if any(is_error(a) for a in args_i):
                    out2[i] = ERROR
                    continue
                try:
                    out2[i] = self._fun(*args_i, **kwargs_i)
                except Exception as e:
                    out2[i] = self._row_error(e)
            return out2
        return out


class AsyncApplyExpression(ApplyExpression):
    """Marker subclass: ``fun`` is a coroutine function, executed on the host
    event loop off the device path (reference async_apply_table,
    src/python_api.rs:2476)."""

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        import asyncio

        from .error_value import ERROR, is_error

        arg_arrays = [a._eval(ctx) for a in self._args]
        kwarg_arrays = {k: v._eval(ctx) for k, v in self._kwargs.items()}
        out = np.empty(ctx.n, dtype=object)
        # mirror the sync path's input handling: Error inputs propagate as
        # ERROR without invoking the UDF; None propagates when requested
        run_rows = []
        for i in range(ctx.n):
            args_i = [a[i] for a in arg_arrays]
            kwargs_i = {k: v[i] for k, v in kwarg_arrays.items()}
            if any(is_error(a) for a in args_i) or any(
                is_error(v) for v in kwargs_i.values()
            ):
                out[i] = ERROR
            elif self._propagate_none and (
                any(a is None for a in args_i)
                or any(v is None for v in kwargs_i.values())
            ):
                out[i] = None
            else:
                run_rows.append((i, args_i, kwargs_i))

        async def run_all():
            coros = [
                self._fun(*args_i, **kwargs_i) for _, args_i, kwargs_i in run_rows
            ]
            return await asyncio.gather(*coros, return_exceptions=True)

        if run_rows:
            # operator identity captured BEFORE dispatch: completions may be
            # handled off the engine thread, where the thread-local is unset
            from .error_log import current_operator_id

            op_id = current_operator_id()
            results = asyncio.run(run_all())
            for (i, _, _), r in zip(run_rows, results):
                if isinstance(r, Exception):
                    out[i] = self._row_error(r, op_id=op_id)
                elif isinstance(r, BaseException):
                    raise r  # cancellation/system exit must not become data
                else:
                    out[i] = r
        return out


class IfElseExpression(ColumnExpression):
    def __init__(self, if_, then, else_):
        from .trace import trace_user_frame

        def branch(v):
            # None is a legitimate branch VALUE here (smart_coerce treats it
            # as "absent" elsewhere)
            return ColumnConstExpression(None) if v is None else smart_coerce(v)

        self._if = branch(if_)
        self._then = branch(then)
        self._else = branch(else_)
        self._deps = (self._if, self._then, self._else)
        self._trace = trace_user_frame()

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        c = self._if._eval(ctx)
        t = self._then._eval(ctx)
        e = self._else._eval(ctx)
        if _is_object(t) or _is_object(e) or _is_object(c):
            return _rowwise(
                lambda ci, ti, ei: ti if ci else ei,
                c, t, e, n=ctx.n, trace=self._trace,
            )
        return np.where(c.astype(bool), t, e)


class IsNoneExpression(ColumnExpression):
    def __init__(self, expr):
        self._expr = smart_coerce(expr)
        self._deps = (self._expr,)

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        v = self._expr._eval(ctx)
        if _is_object(v):
            return np.array([x is None for x in v], dtype=bool)
        return np.zeros(ctx.n, dtype=bool)


class IsNotNoneExpression(IsNoneExpression):
    def _eval(self, ctx: EvalContext) -> np.ndarray:
        return ~super()._eval(ctx)


class CastExpression(ColumnExpression):
    def __init__(self, expr, target: Any):
        self._expr = smart_coerce(expr)
        self._target = dt.wrap(target)
        self._deps = (self._expr,)

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        v = self._expr._eval(ctx)
        npdt = dt.numpy_dtype_for(self._target)
        if npdt is not None and not _is_object(v):
            return v.astype(npdt)
        if npdt is not None:
            caster = {dt.INT: int, dt.FLOAT: float, dt.BOOL: bool}.get(
                dt.unoptionalize(self._target)
            )
            if caster is not None:
                return np.array([None if x is None else caster(x) for x in v], dtype=object)
        return v


class DeclareTypeExpression(CastExpression):
    """``pw.declare_type`` — retypes the column in the schema only; values
    pass through untouched (reference internals/common.py:215)."""

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        return self._expr._eval(ctx)


class FillErrorExpression(ColumnExpression):
    """``pw.fill_error(col, replacement)`` — Error cells replaced per row
    (reference internals/common.py:438; Value::Error, src/engine/value.rs:225)."""

    def __init__(self, expr, replacement):
        self._expr = smart_coerce(expr)
        self._replacement = smart_coerce(replacement)
        self._deps = (self._expr, self._replacement)

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        from .error_value import is_error

        v = self._expr._eval(ctx)
        if not _is_object(v):
            return v
        if not any(is_error(x) for x in v):
            return v
        r = self._replacement._eval(ctx)
        out = v.copy()
        for i in range(ctx.n):
            if is_error(out[i]):
                out[i] = r[i]
        return out


class ConvertExpression(CastExpression):
    """Value conversion (e.g. Json → typed), reference `.as_int()` etc."""

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        v = self._expr._eval(ctx)
        target = dt.unoptionalize(self._target)
        caster = {dt.INT: int, dt.FLOAT: float, dt.BOOL: bool, dt.STR: str}.get(target)
        if caster is None:
            return v
        if not _is_object(v):
            npdt = dt.numpy_dtype_for(target)
            return v.astype(npdt) if npdt is not None else v
        return np.array(
            [None if x is None else caster(x) for x in v], dtype=object
        )


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args):
        self._args = tuple(
            ColumnConstExpression(None) if a is None else smart_coerce(a) for a in args
        )
        self._deps = self._args

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        arrays = [a._eval(ctx) for a in self._args]
        out = np.empty(ctx.n, dtype=object)
        for i in range(ctx.n):
            val = None
            for a in arrays:
                if a[i] is not None:
                    val = a[i]
                    break
            out[i] = val
        if all(not _is_object(a) for a in arrays):
            return arrays[0]
        return out


class RequireExpression(ColumnExpression):
    def __init__(self, val, *args):
        self._val = smart_coerce(val)
        self._args = tuple(smart_coerce(a) for a in args)
        self._deps = (self._val,) + self._args

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        arrays = [a._eval(ctx) for a in self._args]
        v = self._val._eval(ctx)
        out = np.empty(ctx.n, dtype=object)
        for i in range(ctx.n):
            out[i] = None if any(a[i] is None for a in arrays) else v[i]
        return out


class PointerExpression(ColumnExpression):
    """``table.pointer_from(*cols)`` — key derivation expression."""

    def __init__(self, table, *args, instance=None, optional: bool = False):
        self._table = table
        self._args = tuple(smart_coerce(a) for a in args)
        self._instance = smart_coerce(instance) if instance is not None else None
        self._optional = optional
        self._deps = self._args + ((self._instance,) if self._instance is not None else ())

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        from . import keys as keymod

        arrays = [a._eval(ctx) for a in self._args]
        if self._instance is not None:
            arrays = [self._instance._eval(ctx)] + arrays
        return keymod.ref_scalars_batch(arrays) if arrays else keymod.sequential_keys(0, ctx.n)


class ReducerExpression(ColumnExpression):
    """A reducer applied inside groupby().reduce(...) — evaluated by the
    grouped operator, not row-wise (engine/operators/groupby.py)."""

    def __init__(self, reducer, *args, **kwargs):
        self._reducer = reducer
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = kwargs
        self._deps = self._args

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        raise RuntimeError(
            f"reducer {self._reducer} can only be used inside groupby(...).reduce(...)"
        )


def collect_reducers(expr) -> list:
    """All ReducerExpression nodes inside ``expr`` (not descending into
    them) — compound reduce outputs like ``sum(x) / count()`` contain several
    (reference: such expressions are legal reduce outputs,
    internals/groupbys.py)."""
    found: list = []

    def walk(e):
        if isinstance(e, ReducerExpression):
            found.append(e)
            return
        if isinstance(e, ColumnExpression):
            for d in e._deps:
                walk(d)

    walk(expr)
    return found


def expr_equal(a, b) -> bool:
    """Structural equality of expression trees (same node classes, same
    table/name for references, same constants, same children) — used to
    recognize a reduce output that RE-STATES a grouping expression
    (``groupby(t.a % 2).reduce(parity=t.a % 2)``)."""
    if a is b:
        return True
    if type(a) is not type(b):
        return False
    if not isinstance(a, ColumnExpression):
        return a == b
    if isinstance(a, ColumnReference):
        return a._table is b._table and a._name == b._name
    if isinstance(a, ColumnConstExpression):
        return type(a._value) is type(b._value) and a._value == b._value
    if len(a._deps) != len(b._deps):
        return False
    for attr, val in vars(a).items():
        other = getattr(b, attr, None)
        if isinstance(val, ColumnExpression):
            if not expr_equal(val, other):
                return False
        elif isinstance(val, tuple):
            if not isinstance(other, tuple) or len(val) != len(other):
                return False
            for x, y in zip(val, other):
                if isinstance(x, ColumnExpression):
                    if not expr_equal(x, y):
                        return False
                elif x != y:
                    return False
        elif callable(val):
            if val is not other:
                return False
        elif isinstance(val, (str, int, float, bool, type(None))):
            if val != other:
                return False
    return True


def substitute(expr, mapping: dict):
    """Clone ``expr`` with nodes replaced per ``mapping`` (id(node) ->
    replacement expression).  Rewrites every expression-valued attribute
    (including the ``_deps`` mirror) on shallow copies, so arbitrary node
    classes survive without per-class cases."""
    import copy as _copy

    def walk(e):
        if not isinstance(e, ColumnExpression):
            return e
        if id(e) in mapping:
            return mapping[id(e)]
        if not e._deps:
            return e
        clone = _copy.copy(e)
        for attr, val in vars(e).items():
            if isinstance(val, ColumnExpression):
                setattr(clone, attr, walk(val))
            elif isinstance(val, tuple) and any(
                isinstance(v, ColumnExpression) for v in val
            ):
                setattr(
                    clone,
                    attr,
                    tuple(
                        walk(v) if isinstance(v, ColumnExpression) else v
                        for v in val
                    ),
                )
            elif isinstance(val, dict) and any(
                isinstance(v, ColumnExpression) for v in val.values()
            ):
                setattr(
                    clone,
                    attr,
                    {
                        k: walk(v) if isinstance(v, ColumnExpression) else v
                        for k, v in val.items()
                    },
                )
        return clone

    return walk(expr)


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args):
        self._args = tuple(smart_coerce(a) for a in args)
        self._deps = self._args

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        arrays = [a._eval(ctx) for a in self._args]
        out = np.empty(ctx.n, dtype=object)
        for i in range(ctx.n):
            out[i] = tuple(a[i] for a in arrays)
        return out


class GetExpression(ColumnExpression):
    def __init__(self, obj, index, default=None, check: bool = False):
        self._obj = smart_coerce(obj)
        self._index = smart_coerce(index)
        self._default = smart_coerce(default) if default is not None else None
        self._check = check
        self._deps = tuple(
            d for d in (self._obj, self._index, self._default) if d is not None
        )

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        obj = self._obj._eval(ctx)
        idx = self._index._eval(ctx)
        dfl = self._default._eval(ctx) if self._default is not None else None
        out = np.empty(ctx.n, dtype=object)
        for i in range(ctx.n):
            o, j = obj[i], idx[i]
            try:
                if isinstance(o, dict):
                    out[i] = o.get(j) if self._check else o[j]
                    if out[i] is None and self._check and dfl is not None:
                        out[i] = dfl[i] if j not in o else o[j]
                elif o is None:
                    if self._check:
                        out[i] = dfl[i] if dfl is not None else None
                    else:
                        raise TypeError("cannot index None")
                else:
                    out[i] = o[j]
            except (KeyError, IndexError, TypeError):
                if self._check:
                    out[i] = dfl[i] if dfl is not None else None
                else:
                    raise
        return out


class MethodCallExpression(ColumnExpression):
    """A namespaced method on an expression (``x.dt.hour()``, ``x.str.upper()``).

    ``fun`` receives scalar(s); ``vector_fun`` — if given — receives the whole
    array (vectorized path)."""

    def __init__(
        self,
        name: str,
        args: Sequence[Any],
        fun: Callable,
        return_type: Any = None,
        vector_fun: Optional[Callable] = None,
    ):
        from .trace import trace_user_frame

        self._method_name = name
        self._args = tuple(smart_coerce(a) for a in args)
        self._fun = fun
        self._vector_fun = vector_fun
        self._return_type = dt.wrap(return_type) if return_type is not None else dt.ANY
        self._deps = self._args
        self._trace = trace_user_frame()

    def _eval(self, ctx: EvalContext) -> np.ndarray:
        arrays = [a._eval(ctx) for a in self._args]
        if self._vector_fun is not None:
            try:
                return np.asarray(self._vector_fun(*arrays))
            except Exception:
                pass
        npdt = dt.numpy_dtype_for(self._return_type)
        try:
            out = np.empty(ctx.n, dtype=npdt if npdt is not None else object)
            for i in range(ctx.n):
                out[i] = self._fun(*(a[i] for a in arrays))
            return out
        except Exception:
            return _rowwise(self._fun, *arrays, n=ctx.n, trace=self._trace)
