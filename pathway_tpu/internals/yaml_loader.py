"""YAML template config loader
(reference: python/pathway/internals/yaml_loader.py:74-218 — ``$variables``
and ``!pw.<path>`` tags instantiating python objects, used by RAG app
templates; see /root/repo/templates/).

Construction is two-pass: the YAML is first parsed into plain data with
``!pw.`` tags held as deferred nodes, then ``$variables`` are substituted,
then objects instantiate bottom-up — so variables work inside constructor
arguments, and anchors (&x / *x) share ONE constructed object."""

from __future__ import annotations

import importlib
from typing import Any, Dict, IO, Union

import yaml

__all__ = ["load_yaml", "PathwayYamlLoader"]


class PathwayYamlLoader(yaml.SafeLoader):
    pass


def _resolve_callable(path: str) -> Any:
    """Resolve a dotted path like ``pw.xpacks.llm.embedders.TpuEmbedder``."""
    parts = path.split(".")
    if parts[0] in ("pw", "pathway", "pathway_tpu"):
        module_name = "pathway_tpu"
        parts = parts[1:]
    else:
        module_name = parts[0]
        parts = parts[1:]
    import types

    obj = importlib.import_module(module_name)
    for part in parts:
        if hasattr(obj, part):
            obj = getattr(obj, part)
        elif isinstance(obj, types.ModuleType):
            # walk into a submodule not imported by the parent package
            obj = importlib.import_module(obj.__name__ + "." + part)
        else:
            raise AttributeError(f"{obj!r} has no attribute {part!r} in {path}")
    return obj


class _Deferred:
    """A ``!pw.<path>`` node awaiting variable substitution before
    instantiation."""

    __slots__ = ("path", "kind", "payload")

    def __init__(self, path: str, kind: str, payload: Any):
        self.path = path
        self.kind = kind
        self.payload = payload


def _construct_pw_object(loader: PathwayYamlLoader, tag_suffix: str, node: yaml.Node):
    # the registered "!pw." prefix is stripped by yaml before we see the
    # suffix, so "xpacks.llm..." is relative to pathway_tpu unless the user
    # spelled a full module root themselves
    if tag_suffix.split(".")[0] not in ("pw", "pathway", "pathway_tpu"):
        tag_suffix = "pw." + tag_suffix
    if isinstance(node, yaml.MappingNode):
        return _Deferred(
            tag_suffix, "map", loader.construct_mapping(node, deep=True)
        )
    if isinstance(node, yaml.SequenceNode):
        return _Deferred(
            tag_suffix, "seq", loader.construct_sequence(node, deep=True)
        )
    return _Deferred(tag_suffix, "scalar", loader.construct_scalar(node))


yaml.add_multi_constructor("!pw.", _construct_pw_object, Loader=PathwayYamlLoader)
yaml.add_multi_constructor("!pw:", _construct_pw_object, Loader=PathwayYamlLoader)


def _instantiate(
    obj: Any,
    variables: Dict[str, Any],
    memo: Dict[int, Any],
    _visiting: tuple = (),
) -> Any:
    """Bottom-up: substitute $variables, then build deferred objects.  The
    memo keeps anchored (&x / *x) deferred nodes single-instance."""
    if isinstance(obj, _Deferred):
        if id(obj) in memo:
            return memo[id(obj)]
        target = _resolve_callable(obj.path)
        payload = _instantiate(obj.payload, variables, memo, _visiting)
        if obj.kind == "map":
            result = target(**payload)
        elif obj.kind == "seq":
            result = target(*payload)
        elif payload in (None, ""):
            result = target() if callable(target) else target
        else:
            result = target(payload)
        memo[id(obj)] = result
        return result
    if isinstance(obj, dict):
        return {
            k: _instantiate(v, variables, memo, _visiting)
            for k, v in obj.items()
        }
    if isinstance(obj, list):
        return [_instantiate(v, variables, memo, _visiting) for v in obj]
    if isinstance(obj, str) and obj.startswith("$"):
        name = obj[1:]
        if name in variables:
            if name in _visiting:
                chain = " -> ".join((*_visiting, name))
                raise ValueError(
                    f"circular $variable reference in template: {chain}"
                )
            # a variable may itself be (or contain) a deferred object; the
            # memo keeps it single-instance across references
            return _instantiate(
                variables[name], variables, memo, (*_visiting, name)
            )
    return obj


def load_yaml(stream: Union[str, IO]) -> Any:
    """Load a template config; top-level ``$name: value`` entries define
    variables referenced as ``$name`` anywhere — including inside ``!pw.``
    constructor arguments."""
    data = yaml.load(stream, Loader=PathwayYamlLoader)
    if not isinstance(data, dict):
        return _instantiate(data, {}, {})
    variables = {
        k[1:]: v
        for k, v in data.items()
        if isinstance(k, str) and k.startswith("$")
    }
    memo: Dict[int, Any] = {}
    data = {
        k: v
        for k, v in data.items()
        if not (isinstance(k, str) and k.startswith("$"))
    }
    return _instantiate(data, variables, memo)
