"""YAML template config loader
(reference: python/pathway/internals/yaml_loader.py:74-218 — ``$variables``
and ``!pw.<path>`` tags instantiating python objects, used by RAG app
templates)."""

from __future__ import annotations

import importlib
from typing import Any, Dict, IO, Union

import yaml

__all__ = ["load_yaml", "PathwayYamlLoader"]


class PathwayYamlLoader(yaml.SafeLoader):
    pass


def _resolve_callable(path: str) -> Any:
    """Resolve a dotted path like ``pw.xpacks.llm.embedders.SentenceTransformerEmbedder``."""
    parts = path.split(".")
    if parts[0] in ("pw", "pathway", "pathway_tpu"):
        module_name = "pathway_tpu"
        parts = parts[1:]
    else:
        module_name = parts[0]
        parts = parts[1:]
    obj = importlib.import_module(module_name)
    for i, part in enumerate(parts):
        if hasattr(obj, part):
            obj = getattr(obj, part)
        else:
            module_name = module_name + "." + part
            obj = importlib.import_module(module_name)
    return obj


def _construct_pw_object(loader: PathwayYamlLoader, tag_suffix: str, node: yaml.Node):
    target = _resolve_callable(tag_suffix)
    if isinstance(node, yaml.MappingNode):
        kwargs = loader.construct_mapping(node, deep=True)
        return target(**kwargs)
    if isinstance(node, yaml.SequenceNode):
        args = loader.construct_sequence(node, deep=True)
        return target(*args)
    value = loader.construct_scalar(node)
    if value in (None, ""):
        return target() if callable(target) else target
    return target(value)


yaml.add_multi_constructor("!pw.", _construct_pw_object, Loader=PathwayYamlLoader)
yaml.add_multi_constructor("!pw:", _construct_pw_object, Loader=PathwayYamlLoader)


def _resolve_variables(obj: Any, variables: Dict[str, Any]) -> Any:
    if isinstance(obj, dict):
        return {k: _resolve_variables(v, variables) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_resolve_variables(v, variables) for v in obj]
    if isinstance(obj, str) and obj.startswith("$"):
        name = obj[1:]
        if name in variables:
            return variables[name]
    return obj


def load_yaml(stream: Union[str, IO]) -> Any:
    """Load a template config; top-level ``$name: value`` entries define
    variables referenced as ``$name`` elsewhere."""
    data = yaml.load(stream, Loader=PathwayYamlLoader)
    if not isinstance(data, dict):
        return data
    variables = {k[1:]: v for k, v in data.items() if isinstance(k, str) and k.startswith("$")}
    data = {k: v for k, v in data.items() if not (isinstance(k, str) and k.startswith("$"))}
    return _resolve_variables(data, variables)
