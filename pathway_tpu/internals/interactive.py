"""Interactive (REPL/notebook) mode — live table snapshots.

Reference: python/pathway/internals/interactive.py — ``enable_interactive_mode``
starts the computation on a background thread and ``LiveTable`` objects render
the *current* state of a table whenever displayed.  Here the eager engine
already keeps each table's accumulated state in its engine store, so a
LiveTable is a display handle: it (re)drives the executor on a daemon thread
(streaming sources keep ticking) and snapshots the store on render.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["LiveTable", "enable_interactive_mode", "is_interactive_mode_enabled"]

_controller: Optional["InteractiveModeController"] = None


class InteractiveModeController:
    """Owns the background run thread started by ``enable_interactive_mode``."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def ensure_running(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return

            def _drive():
                from . import run as run_mod

                try:
                    run_mod.run(monitoring_level=None)
                except Exception:  # surfaced via the error log, not the REPL
                    import logging

                    logging.getLogger("pathway_tpu.interactive").exception(
                        "interactive run failed"
                    )

            self._thread = threading.Thread(
                target=_drive, name="pathway-interactive", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        from . import run as run_mod

        run_mod.terminate()
        with self._lock:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None


def enable_interactive_mode() -> InteractiveModeController:
    """Turn on interactive mode (reference internals/interactive.py:203).
    After this, ``LiveTable.create(t)`` / ``t.live()`` return live views."""
    global _controller
    if _controller is None:
        _controller = InteractiveModeController()
    return _controller


def is_interactive_mode_enabled() -> bool:
    return _controller is not None


class LiveTable:
    """A live, displayable view of a table (reference ``pw.LiveTable``,
    internals/interactive.py:130).  ``str()`` / ``_repr_html_`` show the
    current snapshot; the backing computation runs on a daemon thread."""

    def __init__(self, table, *, settle_ms: int = 0):
        if _controller is None:
            raise RuntimeError(
                "interactive mode is not enabled; call pw.enable_interactive_mode()"
            )
        self._table = table
        _controller.ensure_running()
        if settle_ms:
            time.sleep(settle_ms / 1000.0)

    @classmethod
    def create(cls, table) -> "LiveTable":
        return cls(table)

    def snapshot(self):
        """(keys, {column: values}) of the current accumulated state."""
        return self._table._materialize()

    def to_pandas(self):
        import pandas as pd

        from .keys import Pointer

        keys, columns = self.snapshot()
        df = pd.DataFrame({name: list(col) for name, col in columns.items()})
        df.index = [Pointer(k) for k in keys]
        return df

    def __str__(self) -> str:
        keys, columns = self.snapshot()
        names = list(columns.keys())
        header = ["id"] + names
        rows = [
            [f"^{int(k) % 0xFFFFFF:X}"] + [str(columns[c][i]) for c in names]
            for i, k in enumerate(keys)
        ]
        widths = [
            max(len(header[c]), *(len(r[c]) for r in rows)) if rows else len(header[c])
            for c in range(len(header))
        ]
        out = [" | ".join(h.ljust(w) for h, w in zip(header, widths))]
        out += [" | ".join(v.ljust(w) for v, w in zip(r, widths)) for r in rows]
        return "\n".join(out)

    __repr__ = __str__

    def _repr_html_(self) -> str:
        return self.to_pandas()._repr_html_()
