"""Static universe solver — build-time key-set consistency proofs.

The reference proves subset/equality/disjointness relations between table
key sets with a SAT solver over implication clauses
(python/pathway/internals/universe_solver.py: subset(A,B) becomes the
clause ¬A ∨ B on pysat).  Every clause that code base ever emits is a Horn
implication, so the same proofs fall out of plain transitive closure over
an implication graph — no SAT dependency, same answers, and queries stay
O(edges) with memoized closures.

Relations registered at graph build time (Universe construction +
pw.universes promises); queries gate operations like ``update_cells`` so a
provably-inconsistent graph fails at CONSTRUCTION, not at tick time.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Set

__all__ = ["UniverseSolver", "get_solver"]


class UniverseSolver:
    def __init__(self):
        # subset -> supersets (one implication edge per registered relation)
        self._edges: Dict[int, Set[int]] = {}
        self._disjoint: Set[FrozenSet[int]] = set()
        self._closure_cache: Dict[int, FrozenSet[int]] = {}

    # -- registration ------------------------------------------------------
    def register_subset(self, sub: int, sup: int) -> None:
        self._edges.setdefault(sub, set()).add(sup)
        self._closure_cache.clear()

    def register_equal(self, a: int, b: int) -> None:
        self.register_subset(a, b)
        self.register_subset(b, a)

    def register_disjoint(self, a: int, b: int) -> None:
        self._disjoint.add(frozenset((a, b)))

    # -- queries -----------------------------------------------------------
    def supersets(self, u: int) -> FrozenSet[int]:
        """u plus every universe reachable over subset edges."""
        cached = self._closure_cache.get(u)
        if cached is not None:
            return cached
        seen: Set[int] = {u}
        stack = [u]
        while stack:
            for nxt in self._edges.get(stack.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        out = frozenset(seen)
        self._closure_cache[u] = out
        return out

    def query_is_subset(self, sub: int, sup: int) -> bool:
        return sup in self.supersets(sub)

    def query_are_equal(self, a: int, b: int) -> bool:
        return self.query_is_subset(a, b) and self.query_is_subset(b, a)

    def query_are_disjoint(self, a: int, b: int) -> bool:
        """Provably disjoint: some registered disjoint pair (X, Y) covers
        them (a ⊆ X and b ⊆ Y, either orientation)."""
        sup_a = self.supersets(a)
        sup_b = self.supersets(b)
        for pair in self._disjoint:
            if len(pair) == 1:
                continue
            x, y = tuple(pair)
            if (x in sup_a and y in sup_b) or (y in sup_a and x in sup_b):
                return True
        return False


    def clear(self) -> None:
        """Forget every relation (pw.reset(): universes die with the graph;
        without this, edges accumulate unboundedly across rebuilds)."""
        self._edges.clear()
        self._disjoint.clear()
        self._closure_cache.clear()


_solver = UniverseSolver()


def get_solver() -> UniverseSolver:
    return _solver
